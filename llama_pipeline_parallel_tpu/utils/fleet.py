"""Fleet observatory: cross-process aggregation of a supervised pod
(docs/OBSERVABILITY.md "Fleet").

The repo's observability so far is per-process — spans/goodput (PR 1),
numerics (PR 3), timelines/perf-ledger/triggered capture (PR 14) all live
in ONE run directory. A pod is many of those at once: a supervised trainer
plus N serve replicas, each with its own supervisor, health.json, and
metrics stream. MPMD pipeline training at scale (PAPERS.md, arxiv
2412.14374) fails in exactly the cross-process seams no single directory
shows: a replica whose heartbeat went stale, a serve tier lagging the
trainer's checkpoints, goodput bleeding away across restarts. This module
is the rollup:

- **Registry contract**: every supervisor launch appends one row to
  `<fleet-root>/registry.jsonl` (`register_member`) — role, replica id,
  output_dir, pid, incarnation, layout. The registry is append-only and
  tolerant-read; the newest row per (output_dir, health_file) wins.
- **Incremental tailing**: `JsonlTailer` (offset-tracking, torn-tail
  carry, `perf.read_jsonl` parse semantics per line) and `FileWatcher`
  (stat-gated whole-file JSON) — a refresh reads only bytes written since
  the previous one, never the whole history. `bytes_read` is the proof a
  test pins.
- **`FleetAggregator`**: discovers members from the registry, tails each
  member's health.json / metrics.jsonl / incarnations.jsonl, scans the
  trainer's checkpoint dir for the latest VERIFIED (complete) step, and
  composes one atomic `<fleet-root>/fleet_status.json` — per-member
  heartbeat staleness, trainer step/goodput/step-time percentiles/bubble
  measured-vs-analytic, per-replica TTFT/TPOT/queue-wait/page-pool/
  `slo_breaches`, checkpoint lag, numerics anomaly counts, and pod-level
  goodput across incarnations.
- **Alert rules** (`AlertRules`, the `alerts.*` block): evaluated per
  refresh; state TRANSITIONS (firing/resolved edges, never level spam)
  append to `<fleet-root>/alerts.jsonl`, and a firing edge drops a
  `capture.trigger` file into the member's output dir — the member's
  TriggeredProfiler (utils/profiler.py) polls for it, so a fleet-level
  symptom produces a bounded process-level trace.

Plain stdlib on purpose: tools/fleetd.py and tools/fleet_report.py import
this without jax.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import time
from typing import Any

from llama_pipeline_parallel_tpu.utils import memwatch
from llama_pipeline_parallel_tpu.utils.logging import get_logger
from llama_pipeline_parallel_tpu.utils.perf import read_jsonl

logger = get_logger(__name__)

REGISTRY_NAME = "registry.jsonl"
STATUS_NAME = "fleet_status.json"
ALERTS_NAME = "alerts.jsonl"
# dropped into a MEMBER's output dir by a firing alert; consumed by the
# member's TriggeredProfiler (utils/profiler.py imports this spelling)
CAPTURE_TRIGGER_NAME = "capture.trigger"
HEALTH_NAME = "health.json"
SUPERVISOR_HEALTH_NAME = "supervisor_health.json"

_CKPT_RE = re.compile(r"^checkpoint-(\d+)$")


def _num(x) -> float | None:
    try:
        v = float(x)
    except (TypeError, ValueError):
        return None
    return v if v == v else None


def write_json_atomic(path: str, payload: dict) -> None:
    """tmp + os.replace: a polling reader (GET /fleet, a shell `cat`) can
    never see a torn fleet_status.json — the same contract health.json and
    serve.json already keep."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# registry contract
# ---------------------------------------------------------------------------

def register_member(fleet_root: str, *, output_dir: str,
                    role: str | None = None, replica: str | None = None,
                    pid: int | None = None, incarnation: int | None = None,
                    health_file: str = HEALTH_NAME,
                    **extra: Any) -> dict:
    """Append one member row to `<fleet-root>/registry.jsonl`. One line per
    LAUNCH (a restarted child re-registers with its new pid/incarnation);
    single-line O_APPEND writes keep concurrent supervisors from tearing
    each other's rows. Returns the row written."""
    os.makedirs(fleet_root, exist_ok=True)
    row = {"ts": time.time(),
           "role": role,
           "replica": replica or os.path.basename(os.path.normpath(output_dir)),
           "output_dir": os.path.abspath(output_dir),
           "pid": pid,
           "incarnation": incarnation,
           "health_file": health_file}
    row.update(extra)
    with open(os.path.join(fleet_root, REGISTRY_NAME), "a") as f:
        f.write(json.dumps(row) + "\n")
    return row


def load_registry(fleet_root: str) -> list[dict]:
    """Every parseable registry row (torn tail skipped — the tolerant
    reader's semantics, `perf.read_jsonl`)."""
    return read_jsonl(os.path.join(fleet_root, REGISTRY_NAME),
                      keep=lambda r: "output_dir" in r)


# ---------------------------------------------------------------------------
# incremental readers
# ---------------------------------------------------------------------------

class JsonlTailer:
    """Offset-tracking jsonl tailer: each `poll()` reads only the bytes
    appended since the previous poll, carrying a torn (newline-less) tail
    until its writer finishes the line — the incremental form of
    `perf.read_jsonl`'s skip-what-doesn't-parse rule. A file that SHRANK
    (rotation, a fresh incarnation truncating) resets to offset 0.
    `bytes_read` counts every byte ever read — the no-full-re-read proof
    tests pin."""

    def __init__(self, path: str, max_poll_bytes: int = 8 << 20):
        self.path = path
        self.offset = 0
        self.bytes_read = 0
        self._carry = b""
        self._max_poll = max_poll_bytes

    def poll(self) -> list[dict]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size < self.offset:
            # truncated/rotated under us: start over, drop the stale carry
            self.offset, self._carry = 0, b""
        if size == self.offset:
            return []
        try:
            with open(self.path, "rb") as f:
                f.seek(self.offset)
                chunk = f.read(min(size - self.offset, self._max_poll))
        except OSError:
            return []
        self.offset += len(chunk)
        self.bytes_read += len(chunk)
        data = self._carry + chunk
        lines = data.split(b"\n")
        self._carry = lines.pop()  # b"" after a complete line; else the tear
        rows = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if isinstance(row, dict):
                rows.append(row)
        return rows


class FileWatcher:
    """Stat-gated whole-file JSON reader for atomically-rewritten files
    (health.json): re-reads only when (mtime_ns, size) changed, so a
    refresh against an idle member costs one stat, zero reads. `.data` is
    the last successfully parsed dict (a torn/garbage rewrite keeps the
    previous good value, status `corrupt`)."""

    def __init__(self, path: str):
        self.path = path
        self.data: dict | None = None
        self.status = "missing"
        self.bytes_read = 0
        self._sig: tuple | None = None

    def poll(self) -> dict | None:
        try:
            st = os.stat(self.path)
        except OSError:
            self.status = "missing" if self.data is None else "gone"
            return self.data
        sig = (st.st_mtime_ns, st.st_size)
        if sig == self._sig:
            return self.data
        self._sig = sig
        try:
            with open(self.path) as f:
                raw = f.read()
            self.bytes_read += len(raw)
            parsed = json.loads(raw)
        except (OSError, ValueError):
            self.status = "corrupt"
            return self.data
        if isinstance(parsed, dict):
            self.data, self.status = parsed, "ok"
        else:
            self.status = "corrupt"
        return self.data


def latest_verified_step(checkpoint_root: str) -> int | None:
    """The newest COMPLETE checkpoint step under a trainer's output dir —
    complete means meta.json landed (the PR 2 commit barrier: digests are
    recorded there, and restore verifies them), the same rule
    CheckpointManager.latest_step applies, re-spelled here without jax so
    the aggregator can poll it. Returns None for no-checkpoints-yet."""
    try:
        names = os.listdir(checkpoint_root)
    except OSError:
        return None
    steps = []
    for name in names:
        m = _CKPT_RE.match(name)
        if m and os.path.exists(os.path.join(checkpoint_root, name,
                                             "meta.json")):
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


# ---------------------------------------------------------------------------
# alert rules (the `alerts.*` block)
# ---------------------------------------------------------------------------

ALERT_KEYS = {"heartbeat_stale_s", "goodput_floor", "step_time_p95_s",
              "ttft_p95_ms", "queue_wait_p95_ms", "tenant_ttft_p95_ms",
              "prefix_hit_rate_floor", "checkpoint_lag_steps",
              "nonfinite_steps", "oom_recent"}
# config key -> the rule name edges/status use (the `_s`/`_ms` unit
# suffixes are config spelling, not alert identity)
_RULE_NAMES = {"heartbeat_stale_s": "heartbeat_stale",
               "goodput_floor": "goodput_floor",
               "step_time_p95_s": "step_time_p95",
               "ttft_p95_ms": "ttft_p95",
               "queue_wait_p95_ms": "queue_wait_p95",
               "tenant_ttft_p95_ms": "tenant_ttft_p95",
               "prefix_hit_rate_floor": "prefix_hit_rate",
               "checkpoint_lag_steps": "checkpoint_lag",
               "nonfinite_steps": "nonfinite_steps",
               "oom_recent": "oom_recent"}
_INT_ALERT_KEYS = ("checkpoint_lag_steps", "nonfinite_steps", "oom_recent")
# the dict spelling of one rule: {"threshold": 500, "for_s": 10,
# "cooldown_s": 30} — flap damping without a second config surface
_ALERT_VALUE_KEYS = {"threshold", "for_s", "cooldown_s"}


@dataclasses.dataclass(frozen=True)
class AlertRules:
    """Declarative fleet alert thresholds (unknown keys rejected, the
    `offload.*` house style). None disables a rule. Each value is either
    a bare threshold or `{"threshold": x, "for_s": y, "cooldown_s": z}` —
    `for_s` requires the raw condition to hold continuously that long
    before the alert FIRES (flap damping), and `cooldown_s` suppresses
    re-firing for that long after a resolve (thrash damping). Both
    default to 0, which is bit-identical to the undamped behavior.
    Semantics:

    - heartbeat_stale_s: member heartbeat age (vouched by its latest
      registry row, the supervisor's own staleness rule) above this
      fires. A member whose latest registry row is TERMINAL (the
      supervisor wrote `outcome=aborted` on giving up) fires immediately
      — a dead pod must not look healthy for the staleness window.
    - goodput_floor: a trainer/serve member's cumulative goodput BELOW
      this fires.
    - step_time_p95_s: the trainer's rolling step-time p95 above this.
    - ttft_p95_ms: a serve replica's rolling TTFT p95 above this.
    - queue_wait_p95_ms: a serve replica's rolling queue-wait p95 above
      this (admission latency — the autoscaler's primary borrow signal).
    - tenant_ttft_p95_ms: ONE threshold evaluated per tenant in a serve
      replica's `tenants` map (serve/telemetry.py per-tenant slices);
      each tenant gets its own rule instance named
      `tenant_ttft_p95:<tenant>` — independent fire/resolve edges and
      damping state per tenant, the scaffolding per-tenant SLO classes
      (ROADMAP item 2) will actuate on.
    - prefix_hit_rate_floor: a prefix-caching serve replica's cumulative
      hit rate (prefix_hits / (prefix_hits + prefix_misses), the
      `prefix_hit_rate` metrics field) BELOW this fires — a cache that
      stopped hitting on a shared-prefix workload means the eviction
      churn or the traffic mix changed under the replica. Only evaluated
      when the replica reports the field (prefix cache on, some traffic
      admitted or refused).
    - checkpoint_lag_steps: serve replica's loaded checkpoint step more
      than this many steps behind the trainer's latest verified one.
    - nonfinite_steps: more than this many nonfinite training steps
      (0 = any nonfinite step alerts).
    - oom_recent: fires while a member's newest `oom/` snapshot
      (utils/memwatch.py forensics) postdates its latest registration —
      memory pressure killed THIS incarnation. Threshold 0 = any recent
      OOM alerts; the rule resolves deterministically when the
      supervisor's relaunch re-registers the member (newer `ts` than
      the snapshot).
    """

    heartbeat_stale_s: float | None = None
    goodput_floor: float | None = None
    step_time_p95_s: float | None = None
    ttft_p95_ms: float | None = None
    queue_wait_p95_ms: float | None = None
    tenant_ttft_p95_ms: float | None = None
    prefix_hit_rate_floor: float | None = None
    checkpoint_lag_steps: int | None = None
    nonfinite_steps: int | None = None
    oom_recent: int | None = None
    # rule name -> (for_s, cooldown_s); absent = (0, 0)
    damping: Any = None

    @classmethod
    def from_cfg(cls, node: Any) -> "AlertRules":
        node = node or {}
        if not isinstance(node, dict):
            raise ValueError(f"alerts must be a mapping, e.g. alerts: "
                             f"{{heartbeat_stale_s: 30}} — got {node!r}")
        unknown = set(node) - ALERT_KEYS
        if unknown:
            raise ValueError(f"unknown alerts.* key(s) {sorted(unknown)}; "
                             f"known: {sorted(ALERT_KEYS)}")
        kw: dict[str, Any] = {}
        damping: dict[str, tuple] = {}
        for key in ALERT_KEYS:
            raw = node.get(key)
            if raw is None:
                continue
            if isinstance(raw, dict):
                bad = set(raw) - _ALERT_VALUE_KEYS
                if bad:
                    raise ValueError(
                        f"unknown alerts.{key} key(s) {sorted(bad)}; "
                        f"known: {sorted(_ALERT_VALUE_KEYS)}")
                if raw.get("threshold") is None:
                    raise ValueError(f"alerts.{key} needs a 'threshold' "
                                     f"when spelled as a mapping")
                threshold = raw["threshold"]
                for_s = float(raw.get("for_s", 0.0) or 0.0)
                cooldown_s = float(raw.get("cooldown_s", 0.0) or 0.0)
                if for_s < 0 or cooldown_s < 0:
                    raise ValueError(f"alerts.{key}: for_s/cooldown_s "
                                     f"must be >= 0")
                if for_s or cooldown_s:
                    damping[_RULE_NAMES[key]] = (for_s, cooldown_s)
            else:
                threshold = raw
            kw[key] = (int(threshold) if key in _INT_ALERT_KEYS
                       else float(threshold))
        if damping:
            kw["damping"] = damping
        return cls(**kw)

    def damping_for(self, rule: str) -> tuple:
        """(for_s, cooldown_s) for one rule name; (0, 0) when undamped.
        Per-tenant rule instances (`tenant_ttft_p95:<tenant>`) inherit
        the base rule's damping — the `:` suffix is instance identity,
        not a second config surface."""
        base = rule.split(":", 1)[0]
        return (self.damping or {}).get(base, (0.0, 0.0))

    def evaluate(self, member: dict) -> list[tuple[str, float, float, bool]]:
        """(rule, value, threshold, firing) for every rule whose input
        exists on this member's status — a rule with no observable value
        is NOT evaluated (its prior state persists; absence of data must
        not fabricate a resolution)."""
        out = []
        role = member.get("role")

        def rule(name, value, threshold, firing):
            if value is not None and threshold is not None:
                out.append((name, value, threshold, bool(firing)))

        age = _num(member.get("heartbeat_age_s"))
        # a terminal registration row (supervisor gave up: crash loop,
        # exhausted budget, no rung) is an explicit death notice — stale
        # NOW, not after the staleness window elapses past the abort
        terminal = member.get("terminal_outcome") is not None
        rule("heartbeat_stale", age, self.heartbeat_stale_s,
             age is not None and self.heartbeat_stale_s is not None
             and (terminal or age > self.heartbeat_stale_s))
        if role != "supervisor":
            gp = _num(member.get("goodput"))
            rule("goodput_floor", gp, self.goodput_floor,
                 gp is not None and self.goodput_floor is not None
                 and gp < self.goodput_floor)
        p95 = _num(member.get("step_time_p95"))
        rule("step_time_p95", p95, self.step_time_p95_s,
             p95 is not None and self.step_time_p95_s is not None
             and p95 > self.step_time_p95_s)
        ttft = _num(member.get("ttft_p95_ms"))
        rule("ttft_p95", ttft, self.ttft_p95_ms,
             ttft is not None and self.ttft_p95_ms is not None
             and ttft > self.ttft_p95_ms)
        qw = _num(member.get("queue_wait_p95_ms"))
        rule("queue_wait_p95", qw, self.queue_wait_p95_ms,
             qw is not None and self.queue_wait_p95_ms is not None
             and qw > self.queue_wait_p95_ms)
        # ONE configured threshold, one rule INSTANCE per tenant: each
        # tenant's edge/damping state is independent (a paid-tier breach
        # must not be masked by a healthy free tier resolving)
        tenants = member.get("tenants")
        if isinstance(tenants, dict) and self.tenant_ttft_p95_ms is not None:
            for name in sorted(tenants):
                snap = tenants[name]
                if not isinstance(snap, dict):
                    continue
                tt = _num(snap.get("ttft_p95_ms"))
                rule(f"tenant_ttft_p95:{name}", tt, self.tenant_ttft_p95_ms,
                     tt is not None and tt > self.tenant_ttft_p95_ms)
        # floor rule, like goodput_floor: fires when the value drops BELOW
        # the threshold; absent field (cache off / no traffic yet) is not
        # evaluated — absence of data must not fabricate a firing
        phr = _num(member.get("prefix_hit_rate"))
        rule("prefix_hit_rate", phr, self.prefix_hit_rate_floor,
             phr is not None and self.prefix_hit_rate_floor is not None
             and phr < self.prefix_hit_rate_floor)
        lag = _num(member.get("checkpoint_lag"))
        rule("checkpoint_lag", lag, self.checkpoint_lag_steps,
             lag is not None and self.checkpoint_lag_steps is not None
             and lag > self.checkpoint_lag_steps)
        nf = _num(member.get("nonfinite_steps"))
        rule("nonfinite_steps", nf, self.nonfinite_steps,
             nf is not None and self.nonfinite_steps is not None
             and nf > self.nonfinite_steps)
        oom = _num(member.get("oom_recent"))
        rule("oom_recent", oom, self.oom_recent,
             oom is not None and self.oom_recent is not None
             and oom > self.oom_recent)
        return out


# ---------------------------------------------------------------------------
# per-member tail state
# ---------------------------------------------------------------------------

# trainer metrics-line fields the rollup keeps (last value wins)
_TRAIN_FIELDS = ("loss", "goodput", "bubble_fraction",
                 "bubble_fraction_measured", "step_time", "step_time_p50",
                 "step_time_p95", "nonfinite_steps", "anomaly_count", "mfu",
                 "tokens_per_sec")
# serving metrics-line fields the rollup keeps
_SERVE_FIELDS = ("requests_completed", "requests_rejected", "requests_failed",
                 "requests_page_refused", "slo_breaches", "tokens_generated",
                 "ttft_p50_ms", "ttft_p95_ms", "ttft_p99_ms", "tpot_p50_ms",
                 "tpot_p95_ms", "queue_wait_p50_ms", "queue_wait_p95_ms",
                 "active_slots", "queue_depth", "pages_used", "pages_free",
                 "pages_reserved", "pages_total", "reserved_unbacked",
                 "page_fragmentation", "reserved_gap_bytes",
                 "page_allocations", "prefilling", "prefill_chunks_total",
                 "prefill_tokens_total", "requests_abandoned", "tenants",
                 "prefix_cache", "prefix_hits", "prefix_misses",
                 "prefix_hit_rate", "prefix_cached_tokens",
                 "prefix_shared_pages", "prefix_cow_forks", "pages_cached",
                 "prefix_evictions")
# gateway metrics-line fields the rollup keeps (serve/gateway.py marks its
# lines `"gateway": 1` the way replicas mark theirs `"serving": 1`)
_GATEWAY_FIELDS = ("requests_routed", "requests_retried", "requests_replayed",
                   "requests_hedged", "hedge_wins", "wasted_hedge_tokens",
                   "replay_skipped_tokens", "requests_completed",
                   "requests_failed", "requests_shed", "requests_rejected",
                   "requests_abandoned", "ttft_p50_ms", "ttft_p95_ms",
                   "inflight_total", "replicas_known", "replicas_healthy",
                   "draining")
_STEP_TIME_WINDOW = 64


class _MemberTail:
    """One member's incremental readers + rolled-up scalars."""

    def __init__(self, row: dict):
        self.registered = row          # latest registry row
        self.role: str | None = row.get("role")  # sticky once resolved
        out = row["output_dir"]
        self.output_dir = out
        self.health = FileWatcher(
            os.path.join(out, row.get("health_file") or HEALTH_NAME))
        # a supervisor member shares its CHILD's output dir: tailing the
        # child's metrics/incarnations here would double-read every byte
        # and re-attribute the child's alert inputs to the supervisor —
        # the watchdog's own surface is its heartbeat file alone
        tail_streams = row.get("role") != "supervisor"
        self.metrics = (JsonlTailer(os.path.join(out, "metrics.jsonl"))
                        if tail_streams else None)
        self.incarnations = (
            JsonlTailer(os.path.join(out, "incarnations.jsonl"))
            if tail_streams else None)
        self.train_last: dict = {}
        self.serve_last: dict = {}
        self.gateway_last: dict = {}
        self.step_times: list[float] = []
        self.inc_count = 0
        self.inc_failed = 0
        self.inc_last: dict = {}
        self.resizes = 0

    @property
    def bytes_read(self) -> int:
        return (self.health.bytes_read
                + (self.metrics.bytes_read if self.metrics else 0)
                + (self.incarnations.bytes_read if self.incarnations else 0))

    def poll(self) -> None:
        health = self.health.poll() or {}
        if self.role is None and isinstance(health.get("role"), str):
            self.role = health["role"]
        for m in (self.metrics.poll() if self.metrics else ()):
            if m.get("serving"):
                for k in _SERVE_FIELDS:
                    if k in m:
                        self.serve_last[k] = m[k]
            elif m.get("gateway"):
                for k in _GATEWAY_FIELDS:
                    if k in m:
                        self.gateway_last[k] = m[k]
            else:
                for k in _TRAIN_FIELDS:
                    if k in m:
                        self.train_last[k] = m[k]
                st = _num(m.get("step_time"))
                if st is not None:
                    self.step_times.append(st)
        if len(self.step_times) > _STEP_TIME_WINDOW:
            del self.step_times[:-_STEP_TIME_WINDOW]
        for row in (self.incarnations.poll() if self.incarnations else ()):
            self.inc_count += 1
            self.inc_last = row
            if row.get("outcome") not in ("clean", "supervisor_stopped", None):
                self.inc_failed += 1
            if row.get("resized"):
                self.resizes += 1

    def resolved_role(self) -> str:
        # registry row > live health role > trainer (the only role that
        # never labels itself)
        return self.role or "trainer"


def _percentile(values: list[float], q: float) -> float | None:
    if not values:
        return None
    ordered = sorted(values)
    rank = max(0, min(len(ordered) - 1,
                      int(round(q / 100.0 * (len(ordered) - 1)))))
    return ordered[rank]


# ---------------------------------------------------------------------------
# the aggregator
# ---------------------------------------------------------------------------

class FleetAggregator:
    """Registry-driven fleet rollup. `refresh()` polls every member's
    streams incrementally, evaluates alert rules, appends firing/resolved
    EDGES to alerts.jsonl, drops capture triggers, and atomically rewrites
    fleet_status.json. Single-threaded by design — tools/fleetd.py calls
    it from one loop and hands snapshots to HTTP threads under a lock."""

    def __init__(self, fleet_root: str, rules: AlertRules | None = None,
                 capture_on_alert: bool = True):
        self.fleet_root = fleet_root
        self.rules = rules or AlertRules()
        self.capture_on_alert = capture_on_alert
        self._registry = JsonlTailer(os.path.join(fleet_root, REGISTRY_NAME))
        self._members: dict[tuple, _MemberTail] = {}
        self._alert_state: dict[tuple, dict] = {}
        self.refresh_count = 0
        self.last_status: dict | None = None

    # -- plumbing ----------------------------------------------------------

    @property
    def bytes_read(self) -> int:
        return (self._registry.bytes_read
                + sum(m.bytes_read for m in self._members.values()))

    def _member_key(self, row: dict) -> tuple:
        return (row["output_dir"], row.get("health_file") or HEALTH_NAME)

    def _ingest_registry(self) -> None:
        for row in self._registry.poll():
            # the tailer yields ANY parseable dict line; a row without an
            # output_dir (garbage, a future header) is skipped like a torn
            # line, never a KeyError out of the daemon's refresh loop
            if not isinstance(row.get("output_dir"), str):
                continue
            key = self._member_key(row)
            tail = self._members.get(key)
            if tail is None:
                self._members[key] = _MemberTail(row)
            else:
                tail.registered = row
                if tail.role is None and row.get("role"):
                    tail.role = row["role"]

    # -- one member's status ----------------------------------------------

    def _member_status(self, tail: _MemberTail, now: float) -> dict:
        tail.poll()
        health = tail.health.data or {}
        reg = tail.registered
        # liveness: the newest of (health time, latest registration) — a
        # freshly relaunched child that has not written health yet is
        # vouched for by its registration, the supervisor's own rule
        h_time = _num(health.get("time")) or 0.0
        reg_ts = _num(reg.get("ts")) or 0.0
        age = now - max(h_time, reg_ts) if (h_time or reg_ts) else None
        status: dict[str, Any] = {
            "role": tail.resolved_role(),
            "replica": reg.get("replica"),
            "output_dir": tail.output_dir,
            "pid": reg.get("pid"),
            "incarnation": reg.get("incarnation"),
            "health_status": tail.health.status,
            "heartbeat_age_s": round(age, 3) if age is not None else None,
            "last_step": health.get("last_step"),
            "goodput": _num(health.get("goodput")),
        }
        if reg.get("layout") is not None:
            status["layout"] = reg.get("layout")
        # a terminal registration row (register_member(..., outcome=...)
        # when the supervisor gives up) stops this member counting as
        # fresh: the heartbeat_stale rule fires immediately on it instead
        # of waiting out the staleness window — a dead pod must not look
        # healthy until its heartbeat ages out
        if isinstance(reg.get("outcome"), str):
            status["terminal_outcome"] = reg["outcome"]
        clock = health.get("clock")
        if isinstance(clock, dict):
            status["elapsed_s"] = _num(clock.get("elapsed"))
        # step-time percentiles: the member's own rolling fields when the
        # timeline mode publishes them, else derived from the tailed
        # metrics step_time stream
        p50 = _num(health.get("step_time_p50")) or _percentile(
            tail.step_times, 50)
        p95 = _num(health.get("step_time_p95")) or _percentile(
            tail.step_times, 95)
        if p50 is not None:
            status["step_time_p50"] = round(p50, 4)
        if p95 is not None:
            status["step_time_p95"] = round(p95, 4)
        for key in ("bubble_fraction", "bubble_fraction_measured",
                    "nonfinite_steps", "anomaly_count", "mfu", "loss"):
            val = tail.train_last.get(key, health.get(key))
            if val is not None:
                out_key = ("bubble_fraction_analytic"
                           if key == "bubble_fraction" else key)
                status[out_key] = val
        if tail.serve_last:
            status.update(tail.serve_last)
        if tail.gateway_last:
            status.update(tail.gateway_last)
        if health.get("checkpoint_step") is not None:
            status["checkpoint_step"] = health.get("checkpoint_step")
        elif isinstance(reg.get("checkpoint_step"), int):
            status["checkpoint_step"] = reg["checkpoint_step"]
        if tail.inc_count:
            status["incarnations"] = tail.inc_count
            status["restarts"] = max(tail.inc_count - 1, 0)
            status["failed_incarnations"] = tail.inc_failed
            status["resizes"] = tail.resizes
            status["last_outcome"] = tail.inc_last.get("outcome")
        if tail.resolved_role() != "supervisor":
            # OOM forensics surface (utils/memwatch.py): snapshot count and
            # the recency bit the oom_recent alert rule keys on. A snapshot
            # newer than the latest registration means memory pressure
            # killed THIS incarnation; a relaunch re-registers with a newer
            # ts, flipping the bit back to 0 — the alert resolves on
            # recovery, not by data going missing. Supervisor members share
            # the child's output dir, so only the child publishes these.
            try:
                snaps = [f for f in os.listdir(memwatch.oom_dir(
                    tail.output_dir)) if f.endswith(".json")]
            except OSError:
                snaps = []
            if snaps:
                status["oom_snapshots"] = len(snaps)
            mtime = memwatch.latest_oom_mtime(tail.output_dir)
            if mtime is not None or reg_ts:
                status["oom_recent"] = int(mtime is not None and reg_ts > 0
                                           and mtime > reg_ts)
        if tail.resolved_role() == "supervisor":
            for key in ("restarts", "consecutive_failures", "last_outcome",
                        "child_pid", "watched_dir"):
                if health.get(key) is not None:
                    status[key] = health[key]
        return status

    # -- alerts ------------------------------------------------------------

    def _evaluate_alerts(self, members: dict[tuple, dict],
                         ids: dict[tuple, str], now: float,
                         write: bool = True) -> tuple[dict, list[dict]]:
        alerts: dict[str, dict] = {}
        edges: list[dict] = []
        for key, member in members.items():
            member_id = ids[key]
            for rule, value, threshold, raw in self.rules.evaluate(member):
                state_key = (rule,) + key
                prev = self._alert_state.get(state_key)
                if prev is None:
                    prev = self._alert_state[state_key] = {
                        "firing": False, "since": now,
                        "raw_since": None, "resolved_at": None}
                # flap damping (for_s / cooldown_s, AlertRules docstring):
                # the raw condition must hold continuously for for_s before
                # the alert FIRES, and a resolve suppresses re-firing for
                # cooldown_s. Both default 0 — damped == raw, bit-identical
                # to the undamped evaluator.
                for_s, cooldown_s = self.rules.damping_for(rule)
                if raw:
                    if prev.get("raw_since") is None:
                        prev["raw_since"] = now
                else:
                    prev["raw_since"] = None
                firing = raw and now - prev["raw_since"] >= for_s
                if firing and not prev["firing"] \
                        and prev.get("resolved_at") is not None \
                        and now - prev["resolved_at"] < cooldown_s:
                    firing = False
                transitioned = firing != prev["firing"]
                if transitioned:
                    if not firing:
                        prev["resolved_at"] = now
                    prev["firing"] = firing
                    prev["since"] = now
                    edge = {"ts": now, "alert": rule, "member": member_id,
                            "output_dir": member["output_dir"],
                            "state": "firing" if firing else "resolved",
                            "value": value, "threshold": threshold}
                    edges.append(edge)
                    if write and firing and self.capture_on_alert \
                            and member["role"] != "supervisor":
                        self._drop_capture_trigger(member, edge)
                if prev["firing"] or transitioned:
                    alerts[f"{rule}:{member_id}"] = {
                        "state": "firing" if prev["firing"] else "resolved",
                        "since": prev["since"], "value": value,
                        "threshold": threshold}
        if edges and write:
            with open(os.path.join(self.fleet_root, ALERTS_NAME), "a") as f:
                for edge in edges:
                    f.write(json.dumps(edge) + "\n")
        return alerts, edges

    def _drop_capture_trigger(self, member: dict, edge: dict) -> None:
        """Cross-process triggered capture: leave one trigger file in the
        member's output dir; its TriggeredProfiler consumes it and runs a
        bounded, retention-capped capture. An UNCONSUMED trigger is left
        alone — alerts must not stack captures faster than the member can
        take them (and a dead member picks the file up on relaunch)."""
        path = os.path.join(member["output_dir"], CAPTURE_TRIGGER_NAME)
        if os.path.exists(path):
            return
        try:
            write_json_atomic(path, {"ts": edge["ts"], "alert": edge["alert"],
                                     "member": edge["member"],
                                     "value": edge["value"],
                                     "threshold": edge["threshold"]})
        except OSError as e:
            logger.warning("could not drop capture trigger in %s: %r",
                           member["output_dir"], e)

    # -- the refresh -------------------------------------------------------

    def refresh(self, write: bool = True) -> dict:
        now = time.time()
        self.refresh_count += 1
        bytes_before = self.bytes_read
        self._ingest_registry()
        members: dict[tuple, dict] = {}
        for key, tail in self._members.items():
            members[key] = self._member_status(tail, now)

        # trainer's latest VERIFIED checkpoint -> per-replica lag
        trainer_step = None
        for member in members.values():
            if member["role"] == "trainer":
                step = latest_verified_step(member["output_dir"])
                if step is not None:
                    member["latest_verified_step"] = step
                    trainer_step = (step if trainer_step is None
                                    else max(trainer_step, step))
        if trainer_step is not None:
            for member in members.values():
                loaded = member.get("checkpoint_step")
                if member["role"] == "serve" and isinstance(loaded, int):
                    member["checkpoint_lag"] = max(trainer_step - loaded, 0)

        # one display id per member, shared by the status map, the alert
        # rollup, and the edge rows — replica-name collisions (two dirs
        # with the same basename, no --replica) disambiguate ONCE here,
        # deterministically (registry ingestion order), so an edge and
        # its member entry can never name two different things
        ids: dict[tuple, str] = {}
        for key, member in members.items():
            member_id = f"{member['role']}:{member['replica']}"
            while member_id in ids.values():
                member_id += "+"
            ids[key] = member_id

        alerts, edges = self._evaluate_alerts(members, ids, now, write=write)

        # pod-level goodput across incarnations: each member's health
        # goodput is already cumulative across restarts (RunClock prior=
        # seeding); the pod number weights members by their elapsed wall
        good = elapsed = 0.0
        pod: dict[str, Any] = {
            "members": len(members),
            "trainer_step": trainer_step,
            "alerts_firing": sorted(k for k, v in alerts.items()
                                    if v["state"] == "firing"),
        }
        for member in members.values():
            gp, el = member.get("goodput"), member.get("elapsed_s")
            if member["role"] != "supervisor" and gp is not None and el:
                good += gp * el
                elapsed += el
        if elapsed:
            pod["goodput"] = round(good / elapsed, 4)

        by_id = {ids[key]: member for key, member in members.items()}
        status = {
            "time": now,
            "fleet_root": self.fleet_root,
            "refresh_count": self.refresh_count,
            "bytes_read_total": self.bytes_read,
            "bytes_read_last_refresh": self.bytes_read - bytes_before,
            "members": by_id,
            "pod": pod,
            "alerts": alerts,
            "alert_edges_last_refresh": edges,
        }
        self.last_status = status
        if write:
            try:
                write_json_atomic(
                    os.path.join(self.fleet_root, STATUS_NAME), status)
            except OSError as e:
                logger.warning("fleet_status.json write failed: %r", e)
        return status


def read_alerts(fleet_root: str) -> list[dict]:
    """Every parseable alert edge (tools/fleet_report.py's timeline)."""
    return read_jsonl(os.path.join(fleet_root, ALERTS_NAME),
                      keep=lambda r: "alert" in r)

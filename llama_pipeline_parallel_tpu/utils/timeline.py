"""Measured per-phase timelines — the schedule observatory's measurement
half (docs/OBSERVABILITY.md "Timelines").

The repo's schedule work is a tower of analytic models (sequence-counted
`bubble_fraction`, the preflight step-time score, `transfer_ms_model`);
this layer measures the thing those models predict. The unit-sequence
interpreter (parallel/pipeline.py `_pipeline_units_local`) already
compiles each maximal equal-flag tick run — warmup / steady / drain /
W-drain (parallel/schedule.py `segments`) — into its own `lax.scan`;
with `timeline.enabled: true` it additionally compiles a host-callback
**boundary mark** between segments. Each mark records (boundary index,
pipeline stage, host perf_counter) when that device's execution reaches
the edge, so one blocked step yields, per stage, how long every segment
actually took. From those durations this module derives:

- a per-step `timeline.jsonl` record: per-segment measured durations,
  **bubble_fraction_measured** (each segment's scheduled idle fraction —
  `schedule.segment_stats` — weighted by its MEASURED wall instead of its
  scheduled one) next to the analytic number, per-stage straggler
  z-scores, and host-offload transfer-stall attribution (measured minus
  scheduled share on segments whose W units tier to host);
- the metrics-line / health.json summary fields
  (`bubble_fraction_measured`, rolling `step_time_p50`/`step_time_p95`).

Cost model of the mode itself: each boundary is a device->host callback
plus a scalar select tying it into the carry (values bit-identical ON vs
OFF), and the trainer blocks on every step's loss to attribute marks to
steps — "block-on-boundary when enabled, free when off". OFF compiles no
callback at all: the program is jaxpr-identical to the pre-observatory
interpreter (pinned in tests/test_timeline.py).

The serving tier gets the same treatment per tick (prefill-chunk vs
decode-step split) through `TimelineWriter` directly — serve/engine.py.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import time
from typing import Any

import numpy as np

from llama_pipeline_parallel_tpu.utils.logging import get_logger

logger = get_logger(__name__)

TIMELINE_KEYS = {"enabled", "window"}

# Boundary index of the train step's post-optimizer-update mark
# (parallel/train_step.py) — far above any segment count.
OPTIMIZER_BOUNDARY = 1 << 16


@dataclasses.dataclass(frozen=True)
class TimelineConfig:
    """The `timeline.*` config block, parsed in one place (train.py +
    tools/serve.py agree on the keys; unknown keys rejected like
    `offload.*`)."""

    enabled: bool = False
    window: int = 64  # rolling window for step_time_p50/p95

    @classmethod
    def from_cfg(cls, node: Any) -> "TimelineConfig":
        node = node or {}
        if not isinstance(node, dict):
            raise ValueError(
                f"timeline must be a mapping, e.g. timeline: {{enabled: "
                f"true}} — got {node!r}")
        unknown = set(node) - TIMELINE_KEYS
        if unknown:
            raise ValueError(f"unknown timeline.* key(s) {sorted(unknown)}; "
                             f"known: {sorted(TIMELINE_KEYS)}")
        raw = node.get("window", 64)
        window = 64 if raw is None else int(raw)  # `window:` empty = default
        if window < 2:
            # an explicit 0/1 is a config mistake, not a default request —
            # rejected like the unknown keys above
            raise ValueError(f"timeline.window must be >= 2, got {window}")
        return cls(enabled=bool(node.get("enabled", False)), window=window)


# -- the mark sink (pure_callback target) ------------------------------------

_COLLECTOR: "TimelineCollector | None" = None


def mark_callback(boundary, stage, probe) -> np.float32:
    """The host side of a compiled boundary mark. Must be fast and
    thread-safe (one device executor thread per mesh device calls it):
    a lock-free list append. Returns 0.0 — the compiled side folds it
    into the carry purely for scheduling/DCE anchoring."""
    c = _COLLECTOR
    if c is not None:
        c._marks.append((int(boundary), int(stage), time.perf_counter()))
    return np.float32(0.0)


def install(collector: "TimelineCollector | None") -> None:
    """Point the process-global mark sink at this run's collector (None
    detaches — marks from a still-draining dispatch are then dropped)."""
    global _COLLECTOR
    _COLLECTOR = collector


class SegmentPlan:
    """Host-side description of what the boundary marks delimit: the
    per-flush segment decomposition (labels, scheduled idle accounting,
    offloaded-W counts) of the sequence the interpreter compiled —
    built from the SAME `schedule.segments` grouping, so mark indices and
    compiled scans can never disagree."""

    def __init__(self, pcfg) -> None:
        from llama_pipeline_parallel_tpu.parallel import pipeline as pl
        from llama_pipeline_parallel_tpu.parallel import schedule as usched

        us = pl.flush_unit_schedule(pcfg)
        if us is None:
            raise ValueError(
                f"no segment plan for schedule {pcfg.schedule!r} (gpipe has "
                f"no unit sequence)")
        self.num_stages = int(us.num_stages)
        self.stats = usched.segment_stats(us)
        self.analytic_bubble = usched.analytic_bubble(us)
        self.total_wall_units = sum(s["wall_units"] for s in self.stats)
        self.offload_labels = {s["label"] for s in self.stats
                               if s["offloaded_w_units"]}

    def label_of(self, boundary: int) -> str:
        if boundary == 0:
            return "flush_start"
        if boundary >= OPTIMIZER_BOUNDARY:
            return "optimizer"
        if 1 <= boundary <= len(self.stats):
            return self.stats[boundary - 1]["label"]
        return f"boundary_{boundary}"


class TimelineCollector:
    """Per-step mark aggregation -> one timeline record.

    `begin_step` clears the mark list; the compiled step's callbacks
    append; `end_step` (called after the step's value barrier) groups
    marks per stage, attributes each inter-mark interval to the label of
    the mark that ENDS it, and derives the measured bubble / straggler /
    transfer-stall fields. `plan=None` (gpipe) degrades to step-wall-only
    records."""

    def __init__(self, plan: SegmentPlan | None):
        self.plan = plan
        self._marks: list = []
        self._host_segments: dict[str, float] = {}
        self._t0 = 0.0

    def begin_step(self, step: int) -> None:
        self._marks = []
        self._host_segments = {}
        self._t0 = time.perf_counter()

    def add_host_segment(self, label: str, dur_s: float) -> None:
        """Host-measured phase (e.g. the offloaded optimizer's fused
        update) folded into the record next to the device segments."""
        self._host_segments[label] = self._host_segments.get(label, 0.0) + dur_s

    def end_step(self, step: int) -> dict:
        wall = time.perf_counter() - self._t0
        marks = self._marks
        self._marks = []
        rec: dict[str, Any] = {"step": int(step),
                               "wall_s": round(wall, 6)}
        if self.plan is not None:
            rec["bubble_fraction_analytic"] = round(
                self.plan.analytic_bubble, 6)
        for label, dur in self._host_segments.items():
            rec.setdefault("host_segments", {})[label] = round(dur, 6)
        if not marks or self.plan is None:
            return rec

        plan = self.plan
        # group per stage in arrival-time order (each device's execution is
        # serial, so its marks are already monotone; dp/tp/sp replicas of a
        # stage interleave — per (interval, label) we keep the straggler's
        # i.e. the longest, duration). The optimizer mark (train_step.py,
        # jit level, fires once) is kept OUT of the per-stage streams: its
        # phase starts when the SLOWEST stage finished the pipeline, so
        # measuring it from any one stage's last mark would double-count
        # the straggler's tail into both numbers.
        by_stage: dict[int, list] = collections.defaultdict(list)
        opt_marks: list[float] = []
        last_pipeline_mark = None
        for boundary, stage, t in marks:
            if boundary >= OPTIMIZER_BOUNDARY:
                opt_marks.append(t)
                continue
            by_stage[stage].append((t, boundary))
            if last_pipeline_mark is None or t > last_pipeline_mark:
                last_pipeline_mark = t
        opt_dur = (max(0.0, max(opt_marks) - last_pipeline_mark)
                   if opt_marks and last_pipeline_mark is not None else 0.0)
        label_dur: dict[str, float] = {}
        stage_total: dict[int, float] = {}
        stage_label_dur: dict[str, dict[int, float]] = \
            collections.defaultdict(dict)
        for stage, ms in by_stage.items():
            ms.sort()
            for (t_prev, _), (t, boundary) in zip(ms, ms[1:]):
                label = plan.label_of(boundary)
                d = t - t_prev
                if label == "flush_start":
                    # a later accum flush's opening mark: the gap back to
                    # the previous flush's last boundary is host turnaround,
                    # not schedule time
                    continue
                cur = stage_label_dur[label].get(stage, 0.0)
                stage_label_dur[label][stage] = cur + d
        for label, per_stage in stage_label_dur.items():
            # the segment's lockstep wall is its slowest stage's time
            label_dur[label] = max(per_stage.values())
            for stage, d in per_stage.items():
                stage_total[stage] = stage_total.get(stage, 0.0) + d

        pipeline_s = sum(label_dur.values())
        segs: dict[str, dict] = {}
        bubble_time = 0.0
        transfer_stall = 0.0
        for sstat in plan.stats:
            label = sstat["label"]
            dur = label_dur.get(label)
            if dur is None:
                continue
            busy = sstat["busy_frac"]
            idle_frac = 1.0 - (sum(busy) / len(busy) if busy else 1.0)
            bubble_time += dur * idle_frac
            entry = {"dur_s": round(dur, 6),
                     "share": round(dur / pipeline_s, 4) if pipeline_s else 0.0,
                     "scheduled_share": round(
                         sstat["wall_units"] / plan.total_wall_units, 4)
                     if plan.total_wall_units else 0.0}
            if label in plan.offload_labels and plan.total_wall_units:
                # transfer-stall attribution: wall beyond the segment's
                # scheduled share of the pipeline time, on segments whose W
                # units cross the host link (a heuristic split, not a
                # measurement of the copies themselves — docs/OBSERVABILITY.md)
                expected = (sstat["wall_units"] / plan.total_wall_units
                            * pipeline_s)
                stall = max(dur - expected, 0.0)
                entry["transfer_stall_s"] = round(stall, 6)
                transfer_stall += stall
            segs[label] = entry
        rec["segments"] = segs
        rec["pipeline_s"] = round(pipeline_s, 6)
        if opt_dur:
            rec["optimizer_s"] = round(opt_dur, 6)
        if pipeline_s:
            rec["bubble_fraction_measured"] = round(
                bubble_time / pipeline_s, 6)
        if transfer_stall:
            rec["transfer_stall_s"] = round(transfer_stall, 6)
        if stage_total:
            totals = [stage_total.get(s, 0.0)
                      for s in range(plan.num_stages)]
            mean = float(np.mean(totals))
            std = float(np.std(totals))
            z = [round((t - mean) / std, 3) if std > 1e-12 else 0.0
                 for t in totals]
            rec["stage_time_s"] = [round(t, 6) for t in totals]
            rec["stage_z"] = z
            rec["straggler_stage"] = int(np.argmax(totals))
        return rec


class TimelineWriter:
    """Append-only `timeline.jsonl` sink (process 0). Line-buffered so a
    crashed run's tail is still readable; `read_timeline` tolerates the
    torn final line either way."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self._f = open(path, "a", buffering=1)

    def write(self, record: dict) -> None:
        try:
            self._f.write(json.dumps(record) + "\n")
        except (OSError, ValueError, TypeError):
            logger.exception("timeline write failed (record dropped)")

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def read_timeline(path: str) -> list[dict]:
    """Every parseable record of a timeline.jsonl — missing file, empty
    file, torn tail, or interleaved garbage lines degrade to whatever
    parses (perf.read_jsonl, the one spelling of the tolerant reader)."""
    from llama_pipeline_parallel_tpu.utils.perf import read_jsonl

    return read_jsonl(path)


class StepTimeline:
    """The trainer-side driver: installs the collector around every step,
    blocks on the step's loss (the attribute-marks-to-steps barrier),
    writes timeline.jsonl, and keeps the rolling metrics/health summary
    (`bubble_fraction_measured`, `step_time_p50/p95`)."""

    def __init__(self, pcfg, output_dir: str, write: bool = True,
                 window: int = 64):
        plan = None
        try:
            plan = SegmentPlan(pcfg)
        except ValueError as e:
            logger.warning("timeline: %s — recording step walls only", e)
        self.collector = TimelineCollector(plan)
        self.writer = (TimelineWriter(os.path.join(output_dir,
                                                   "timeline.jsonl"))
                       if write else None)
        self._walls: collections.deque = collections.deque(maxlen=window)
        self._bubbles: list[float] = []
        self.last_record: dict | None = None
        self.health_fields: dict = {}

    @property
    def segmented(self) -> bool:
        return self.collector.plan is not None

    def pre_step(self, step: int) -> None:
        install(self.collector)
        self.collector.begin_step(step)

    def post_step(self, step: int, loss) -> dict:
        import jax

        jax.block_until_ready(loss)
        rec = self.collector.end_step(step)
        self._walls.append(rec["wall_s"])
        if rec.get("bubble_fraction_measured") is not None:
            self._bubbles.append(rec["bubble_fraction_measured"])
        self.last_record = rec
        if self.writer is not None:
            self.writer.write(rec)
        self.health_fields.update(self.scalars())
        return rec

    def add_host_segment(self, label: str, dur_s: float) -> None:
        self.collector.add_host_segment(label, dur_s)

    def scalars(self) -> dict:
        """The metrics-line summary — present only once a window exists,
        so downstream joins never see fabricated zeros."""
        out: dict = {}
        if self._walls:
            walls = list(self._walls)
            out["step_time_p50"] = round(float(np.percentile(walls, 50)), 4)
            out["step_time_p95"] = round(float(np.percentile(walls, 95)), 4)
        if self.last_record and "bubble_fraction_measured" in self.last_record:
            out["bubble_fraction_measured"] = \
                self.last_record["bubble_fraction_measured"]
        return out

    def measured_bubble_median(self) -> float | None:
        """Median of the run's measured bubbles (the perf-ledger pairing
        for the analytic bubble_fraction)."""
        return float(np.median(self._bubbles)) if self._bubbles else None

    def close(self) -> None:
        install(None)
        if self.writer is not None:
            self.writer.close()

"""Three-source memory accounting — the memory observatory
(docs/OBSERVABILITY.md "Memory").

The selection machinery (`preflight --select`, the solver offload
vectors, the 65B frontier) ranks candidates against an *analytic* byte
model, patched by the anchored-compile heuristic ("XLA-CPU over-counts
>2^31-element stash buffers"); PR 14 closed the model-vs-measured loop
for **time** but memory had no measured counterpart. This module is that
counterpart, from three independent sources:

1. **compiled** — `compiled.memory_analysis()` (argument / output /
   temp / alias bytes) plus best-effort top-N buffer attribution from
   the HLO text, captured once per jitted program the run compiles
   (train step, eval, prefill, decode). Available at compile time on
   any backend; degrades to nothing where a backend hides it.
2. **live** — a per-step host-side sampler polling
   `device.memory_stats()` (bytes_in_use / peak / largest alloc on
   TPU), host RSS, and the host-stash/offload resident estimate into an
   opt-in `memory.jsonl`. OFF is zero overhead: the sampler never
   touches the compiled graph (no callback, no extra output — pinned in
   tests/test_memwatch.py like `timeline.enabled`).
3. **serving** — the page-pool occupancy / fragmentation gauges
   (serve/engine.py reads serve/pages.py; this module only defines the
   shared reader + snapshot plumbing).

All three feed the perf ledger (`mem_peak_gib` model-vs-measured rows →
`perf_report --emit-calibration` → `preflight --calibration --mem-scale`)
and the OOM forensics path: `dump_oom_snapshot` writes a bounded
snapshot (last memory rows, compiled analyses, top buffers, page table)
to `<output_dir>/oom/` when a RESOURCE_EXHAUSTED surfaces, which the
supervisor labels as an `oom` outcome and the fleet observatory alerts
on (`oom_recent`).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import time
from typing import Any

from llama_pipeline_parallel_tpu.utils.logging import get_logger

logger = get_logger(__name__)

MEMORY_KEYS = {"enabled", "every", "top_buffers"}

GIB = 1024 ** 3

# Bounded forensics: keep the newest N snapshots, the last M live rows.
OOM_KEEP_SNAPSHOTS = 8
OOM_KEEP_ROWS = 32


@dataclasses.dataclass(frozen=True)
class MemoryConfig:
    """The `memory.*` config block, parsed in one place (train.py +
    tools/serve.py agree on the keys; unknown keys rejected like
    `timeline.*`)."""

    enabled: bool = False
    every: int = 1  # sample every N steps
    top_buffers: int = 8  # HLO buffer attribution depth per program

    @classmethod
    def from_cfg(cls, node: Any) -> "MemoryConfig":
        node = node or {}
        if not isinstance(node, dict):
            raise ValueError(
                f"memory must be a mapping, e.g. memory: {{enabled: "
                f"true}} — got {node!r}")
        unknown = set(node) - MEMORY_KEYS
        if unknown:
            raise ValueError(f"unknown memory.* key(s) {sorted(unknown)}; "
                             f"known: {sorted(MEMORY_KEYS)}")
        raw = node.get("every", 1)
        every = 1 if raw is None else int(raw)  # `every:` empty = default
        if every < 1:
            raise ValueError(f"memory.every must be >= 1, got {every}")
        raw = node.get("top_buffers", 8)
        top = 8 if raw is None else int(raw)
        if top < 0:
            raise ValueError(f"memory.top_buffers must be >= 0, got {top}")
        return cls(enabled=bool(node.get("enabled", False)), every=every,
                   top_buffers=top)


# -- live telemetry (the one spelling; trace.py delegates here) --------------

def device_peak_bytes() -> tuple[int | None, str]:
    """(max peak bytes across local devices, source).

    TPU/GPU report `memory_stats()["peak_bytes_in_use"]`; the CPU backend
    returns None, where the process peak RSS (ru_maxrss) stands in so the
    metrics field exists on every platform — the source tag keeps the two
    from being compared against each other."""
    try:
        import jax

        peaks = []
        for d in jax.local_devices():
            stats = d.memory_stats()
            if stats and stats.get("peak_bytes_in_use") is not None:
                peaks.append(int(stats["peak_bytes_in_use"]))
        if peaks:
            return max(peaks), "device"
    except Exception as e:
        logger.debug("memory_stats unavailable: %r", e)
    try:
        import resource

        # linux reports ru_maxrss in KiB
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024, "host_rss"
    except Exception:
        return None, "unavailable"


def live_sample() -> dict:
    """One host-side poll of every live source: per-device
    bytes_in_use / peak / largest alloc (worst device), host RSS.
    Purely observational — never touches a compiled program."""
    out: dict[str, Any] = {}
    try:
        import jax

        in_use, peak, largest = [], [], []
        for d in jax.local_devices():
            stats = d.memory_stats()
            if not stats:
                continue
            if stats.get("bytes_in_use") is not None:
                in_use.append(int(stats["bytes_in_use"]))
            if stats.get("peak_bytes_in_use") is not None:
                peak.append(int(stats["peak_bytes_in_use"]))
            if stats.get("largest_alloc_size") is not None:
                largest.append(int(stats["largest_alloc_size"]))
        if in_use:
            out["device_bytes_in_use"] = max(in_use)
        if peak:
            out["device_peak_bytes"] = max(peak)
        if largest:
            out["device_largest_alloc"] = max(largest)
    except Exception as e:
        logger.debug("live memory_stats unavailable: %r", e)
    try:
        import resource

        out["host_rss_bytes"] = \
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        pass
    return out


# -- compiled-program analysis ----------------------------------------------

# HLO buffer lines look like
#   `  %fusion.3 = bf16[8,512,8192]{2,1,0} fusion(...)` — the dtype[shape]
# token is enough to rank the program's biggest values for attribution.
_HLO_VALUE = re.compile(
    r"%([\w.\-]+)\s*=\s*([a-z][a-z0-9]*)\[([0-9,]*)\]")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}


def _top_hlo_buffers(hlo_text: str, n: int) -> list[dict]:
    """Best-effort largest-value attribution from the optimized HLO text:
    name, dtype, shape, bytes for the top-n distinct values. A ranking
    aid for "what IS that 40 GiB temp", not an allocator ground truth
    (XLA may alias or split them) — wrapped so an unparseable dump
    degrades to []."""
    if n <= 0:
        return []
    try:
        best: dict[str, dict] = {}
        for m in _HLO_VALUE.finditer(hlo_text):
            name, dtype, dims = m.group(1), m.group(2), m.group(3)
            unit = _DTYPE_BYTES.get(dtype)
            if unit is None:
                continue
            elems = 1
            if dims:
                for d in dims.split(","):
                    elems *= int(d)
            nbytes = elems * unit
            prev = best.get(name)
            if prev is None or nbytes > prev["bytes"]:
                best[name] = {"name": name, "dtype": dtype,
                              "shape": [int(d) for d in dims.split(",")]
                              if dims else [], "bytes": nbytes}
        ranked = sorted(best.values(), key=lambda b: -b["bytes"])[:n]
        return ranked
    except Exception as e:
        logger.debug("HLO buffer attribution failed: %r", e)
        return []


def compiled_memory(compiled, top_buffers: int = 8,
                    label: str = "") -> dict | None:
    """The compile-time memory evidence for one jitted program: the
    `memory_analysis()` aggregates (argument / output / temp / alias
    bytes, peak = arg + out + temp − alias) plus top-N HLO buffer
    attribution. Returns None where the backend hides the analysis —
    callers treat compiled evidence as optional everywhere."""
    try:
        ma = compiled.memory_analysis()
    except Exception as e:
        logger.debug("memory_analysis unavailable (%s): %r", label, e)
        return None
    if ma is None:
        return None
    try:
        arg = int(ma.argument_size_in_bytes)
        out_b = int(ma.output_size_in_bytes)
        temp = int(ma.temp_size_in_bytes)
        alias = int(ma.alias_size_in_bytes)
    except Exception as e:
        logger.debug("memory_analysis attrs unreadable (%s): %r", label, e)
        return None
    rec = {
        "label": label,
        "argument_bytes": arg,
        "output_bytes": out_b,
        "temp_bytes": temp,
        "alias_bytes": alias,
        "generated_bytes": getattr(ma, "generated_code_size_in_bytes", None),
        "peak_bytes": arg + out_b + temp - alias,
    }
    if top_buffers:
        try:
            hlo = compiled.as_text()
        except Exception:
            hlo = ""
        rec["top_buffers"] = _top_hlo_buffers(hlo, top_buffers)
    return rec


# -- the run-side watch ------------------------------------------------------

class MemoryWatch:
    """The trainer/server-side driver: captures compiled analyses (one
    shot per label), samples the live sources on a step cadence into
    `memory.jsonl`, keeps a bounded ring of recent rows for OOM
    snapshots, and pairs compiled-vs-live into perf-ledger rows.

    Everything here is host-side bookkeeping: a MemoryWatch never
    changes what gets compiled or dispatched (the zero-cost pin)."""

    def __init__(self, output_dir: str, every: int = 1,
                 top_buffers: int = 8, write: bool = True,
                 stash_bytes: int | None = None):
        self.every = max(int(every), 1)
        self.top_buffers = int(top_buffers)
        self.stash_bytes = stash_bytes  # host-stash resident estimate
        self.compiled: dict[str, dict] = {}
        self.path = os.path.join(output_dir, "memory.jsonl")
        self._f = None
        if write:
            try:
                os.makedirs(output_dir or ".", exist_ok=True)
                self._f = open(self.path, "a", buffering=1)
            except OSError:
                logger.exception("memory.jsonl open failed (sampling "
                                 "continues unwritten)")
        self._recent: list[dict] = []  # ring for the OOM snapshot
        self.last_sample: dict | None = None

    def note_compiled(self, label: str, compiled) -> dict | None:
        """Record one program's compile-time analysis (first call per
        label wins — re-compiles of the same program would only repeat
        it). `compiled` is a jax Compiled (train step, eval, prefill,
        decode...)."""
        if label in self.compiled:
            return self.compiled[label]
        rec = compiled_memory(compiled, self.top_buffers, label=label)
        if rec is not None:
            self.compiled[label] = rec
            self._write({"kind": "compiled", "time": time.time(), **rec})
            logger.info(
                "compiled memory (%s): peak %.2f GiB (arg %.2f + out %.2f "
                "+ temp %.2f - alias %.2f)", label,
                rec["peak_bytes"] / GIB, rec["argument_bytes"] / GIB,
                rec["output_bytes"] / GIB, rec["temp_bytes"] / GIB,
                rec["alias_bytes"] / GIB)
        return rec

    def sample(self, step: int) -> dict | None:
        """One live poll (respecting the `every` cadence) -> one
        memory.jsonl row. Returns the row (or None when skipped)."""
        if step % self.every != 0:
            return None
        row = {"kind": "sample", "step": int(step), "time": time.time(),
               **live_sample()}
        if self.stash_bytes is not None:
            row["host_stash_bytes"] = int(self.stash_bytes)
        self.last_sample = row
        self._recent.append(row)
        if len(self._recent) > OOM_KEEP_ROWS:
            self._recent = self._recent[-OOM_KEEP_ROWS:]
        self._write(row)
        return row

    def _write(self, rec: dict) -> None:
        if self._f is None:
            return
        try:
            self._f.write(json.dumps(rec) + "\n")
        except (OSError, ValueError, TypeError):
            logger.exception("memory.jsonl write failed (record dropped)")

    def health_gauges(self) -> dict:
        """Live gauges for the metrics line / health.json — present only
        once a sample exists, so downstream joins never see fabricated
        zeros."""
        if not self.last_sample:
            return {}
        out = {}
        for k in ("device_bytes_in_use", "device_peak_bytes",
                  "host_rss_bytes"):
            if self.last_sample.get(k) is not None:
                out[k] = self.last_sample[k]
        return out

    def perf_rows(self, run: str | None = None) -> list[dict]:
        """Perf-ledger pairing: per compiled program a
        `compiled_peak_gib:<label>` row, plus one `mem_peak_gib` row
        with model = the train step's compiled peak, measured = the live
        device peak — the memory analogue of the mfu/bubble rows."""
        from llama_pipeline_parallel_tpu.utils import perf

        rows: list[dict] = []
        for label, rec in self.compiled.items():
            rows.append(perf.make_row(
                f"compiled_peak_gib:{label}",
                model=round(rec["peak_bytes"] / GIB, 3), measured=None,
                unit="GiB", source="memwatch", run=run,
                temp_gib=round(rec["temp_bytes"] / GIB, 3),
                argument_gib=round(rec["argument_bytes"] / GIB, 3)))
        step_rec = (self.compiled.get("train_step")
                    or next(iter(self.compiled.values()), None))
        live_peak = None
        live_src = None
        if self.last_sample and self.last_sample.get("device_peak_bytes"):
            live_peak = self.last_sample["device_peak_bytes"]
            live_src = "device"
        else:
            b, src = device_peak_bytes()
            if b is not None and src == "device":
                live_peak, live_src = b, src
        if step_rec is not None or live_peak is not None:
            rows.append(perf.make_row(
                "mem_peak_gib",
                model=(round(step_rec["peak_bytes"] / GIB, 3)
                       if step_rec is not None else None),
                measured=(round(live_peak / GIB, 3)
                          if live_peak is not None else None),
                unit="GiB", source="memwatch", run=run,
                measured_source=live_src))
        return rows

    def snapshot(self) -> dict:
        """The bounded forensics payload: recent live rows + every
        compiled analysis (top buffers included)."""
        return {"recent": list(self._recent[-OOM_KEEP_ROWS:]),
                "compiled": dict(self.compiled)}

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def read_memory(path: str) -> list[dict]:
    """Every parseable record of a memory.jsonl — missing file, empty
    file, torn tail, or interleaved garbage lines degrade to whatever
    parses (perf.read_jsonl, the one spelling of the tolerant reader)."""
    from llama_pipeline_parallel_tpu.utils.perf import read_jsonl

    return read_jsonl(path)


# -- OOM forensics -----------------------------------------------------------

def is_resource_exhausted(exc: BaseException) -> bool:
    """True for XLA's allocation-failure surface: the exception type name
    or message carries RESOURCE_EXHAUSTED / "out of memory" (jaxlib
    raises XlaRuntimeError with the gRPC-style code prefix; the chaos
    injector raises a plain RuntimeError with the same marker)."""
    text = f"{type(exc).__name__}: {exc}"
    return ("RESOURCE_EXHAUSTED" in text
            or "out of memory" in text.lower()
            or "ResourceExhausted" in type(exc).__name__)


def oom_dir(output_dir: str) -> str:
    return os.path.join(output_dir, "oom")


def dump_oom_snapshot(output_dir: str, step: int | None,
                      error: BaseException | str,
                      memwatch: "MemoryWatch | None" = None,
                      page_table: dict | None = None,
                      extra: dict | None = None) -> str | None:
    """Write one bounded OOM snapshot to `<output_dir>/oom/` — the last
    live rows, every compiled analysis (top buffers included), and the
    page table if a server was involved — atomically (tmp + rename) so a
    watcher never reads a torn file; the newest OOM_KEEP_SNAPSHOTS are
    retained. Swallows its own failures: forensics must never turn an
    OOM abort into a second crash."""
    try:
        d = oom_dir(output_dir)
        os.makedirs(d, exist_ok=True)
        snap: dict[str, Any] = {
            "time": time.time(),
            "step": None if step is None else int(step),
            "error": str(error)[:2000],
            "error_type": (type(error).__name__
                           if isinstance(error, BaseException) else "str"),
            "live": live_sample(),
        }
        if memwatch is not None:
            snap["memwatch"] = memwatch.snapshot()
        if page_table is not None:
            snap["page_table"] = page_table
        if extra:
            snap.update(extra)
        stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime(snap["time"]))
        path = os.path.join(d, f"oom-{stamp}-{os.getpid()}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f, indent=2, default=str)
        os.replace(tmp, path)
        # retention: newest first, drop the tail
        snaps = sorted((p for p in os.listdir(d)
                        if p.startswith("oom-") and p.endswith(".json")),
                       reverse=True)
        for old in snaps[OOM_KEEP_SNAPSHOTS:]:
            try:
                os.remove(os.path.join(d, old))
            except OSError:
                pass
        logger.error("OOM snapshot written: %s", path)
        return path
    except Exception:
        logger.exception("OOM snapshot failed (forensics dropped)")
        return None


def read_oom_snapshots(output_dir: str) -> list[dict]:
    """Every parseable snapshot under `<output_dir>/oom/`, newest first —
    missing dir, torn or garbage files degrade to whatever parses (the
    reader house rule)."""
    d = oom_dir(output_dir)
    out: list[dict] = []
    try:
        names = sorted((p for p in os.listdir(d)
                        if p.startswith("oom-") and p.endswith(".json")),
                       reverse=True)
    except OSError:
        return out
    for name in names:
        try:
            with open(os.path.join(d, name)) as f:
                rec = json.load(f)
            if isinstance(rec, dict):
                rec["_file"] = name
                out.append(rec)
        except (OSError, ValueError):
            continue
    return out


def latest_oom_mtime(output_dir: str) -> float | None:
    """mtime of the newest OOM snapshot, or None — the one spelling the
    supervisor ("crash + fresh snapshot => oom outcome") and the fleet
    alert (`oom_recent`: snapshot newer than the member's registration)
    both compare timestamps against."""
    d = oom_dir(output_dir)
    try:
        times = [os.path.getmtime(os.path.join(d, p))
                 for p in os.listdir(d)
                 if p.startswith("oom-") and p.endswith(".json")]
    except OSError:
        return None
    return max(times) if times else None

"""Triggered, bounded jax.profiler capture windows
(docs/OBSERVABILITY.md "Triggered capture").

The PR 1 `profile_steps` window profiles a step range you pick BEFORE the
run; this layer captures the step you could not have picked — fired by:

- config (`profiler.at_step: [N, ...]` — capture when the loop reaches N);
- step-time z-score outliers (a rolling window of per-step wall times; a
  step `profiler.zscore` standard deviations above the mean starts a
  capture, so the straggler/stall that skews the timeline gets a per-op
  trace attached);
- numerics anomalies (the PR 3 observatory emits zero-duration
  `numerics_anomaly` spans; `TriggeredProfiler.on_span` subscribes to the
  span stream and converts them into captures);
- serving SLO breaches (serve/engine.py calls `trigger()` when a
  completed request blows a configured threshold);
- fleet alerts (docs/OBSERVABILITY.md "Fleet"): a firing fleet-level
  alert (tools/fleetd.py) drops a `capture.trigger` file into this
  process's output dir; `observe_step` polls for it (rate-limited by
  `profiler.trigger_poll_s`), consumes it, and starts a capture — a
  cross-PROCESS symptom produces a bounded process-level trace. A
  trigger dropped while the process was dead fires on the first step
  after relaunch.

Every capture is a bounded window: `profiler.window_steps` observe() calls
(train steps or serve ticks) after which the trace stops, written under
`<output_dir>/captures/step<N>-<reason>/` — readable by
tools/trace_summary.py. `profiler.max_captures` is the retention cap: once
that many captures exist on disk, further triggers are dropped (a pathology
that fires every step must not fill the disk with traces of itself).
A capture never raises into the training/serving loop, and a window open
at loop exit is closed by `close()`.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import re
import time
from typing import Any

import numpy as np

from llama_pipeline_parallel_tpu.utils.fleet import CAPTURE_TRIGGER_NAME
from llama_pipeline_parallel_tpu.utils.logging import get_logger

logger = get_logger(__name__)

PROFILER_KEYS = {"at_step", "window_steps", "max_captures", "zscore",
                 "zscore_window", "zscore_min_history", "trigger_poll_s",
                 "on_anomaly"}


@dataclasses.dataclass(frozen=True)
class CaptureConfig:
    """The `profiler.*` config block (unknown keys rejected, the
    `offload.*` house style). `enabled` is implied by the node's presence:
    an empty node arms only the z-score default."""

    at_step: tuple = ()
    window_steps: int = 2       # observe() calls per capture window
    max_captures: int = 3       # retention cap: captures kept on disk
    zscore: float = 4.0         # 0 disables the outlier trigger
    zscore_window: int = 32     # rolling step-time window
    zscore_min_history: int = 8  # steps before the trigger can arm
    trigger_poll_s: float = 1.0  # capture.trigger poll cadence (fleet)
    on_anomaly: bool = True     # numerics_anomaly spans start captures

    @classmethod
    def from_cfg(cls, node: Any) -> "CaptureConfig | None":
        if node is None:
            return None
        if not isinstance(node, dict):
            raise ValueError(
                f"profiler must be a mapping, e.g. profiler: {{at_step: "
                f"[12]}} — got {node!r}")
        unknown = set(node) - PROFILER_KEYS
        if unknown:
            raise ValueError(f"unknown profiler.* key(s) {sorted(unknown)}; "
                             f"known: {sorted(PROFILER_KEYS)}")
        at = node.get("at_step") or ()
        if isinstance(at, (int, float)):
            at = (at,)
        cfg = cls(at_step=tuple(int(s) for s in at),
                  window_steps=int(node.get("window_steps", 2)),
                  max_captures=int(node.get("max_captures", 3)),
                  zscore=float(node.get("zscore", 4.0)),
                  zscore_window=int(node.get("zscore_window", 32)),
                  zscore_min_history=int(node.get("zscore_min_history", 8)),
                  trigger_poll_s=float(node.get("trigger_poll_s", 1.0)),
                  on_anomaly=bool(node.get("on_anomaly", True)))
        if cfg.window_steps < 1:
            raise ValueError("profiler.window_steps must be >= 1")
        if cfg.max_captures < 1:
            raise ValueError("profiler.max_captures must be >= 1")
        if cfg.zscore_min_history < 2:
            raise ValueError("profiler.zscore_min_history must be >= 2")
        return cfg


def _safe_reason(reason: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]+", "_", reason)[:64] or "capture"


class TriggeredProfiler:
    """Bounded trace-capture state machine. Thread-compatible with the
    serving engine (trigger/observe from the loop thread, on_span from
    whatever thread emits spans) — all transitions funnel through
    `_start`/`_stop`, guarded against double starts and foreign traces."""

    def __init__(self, cfg: CaptureConfig, output_dir: str):
        self.cfg = cfg
        self.dir = os.path.join(output_dir, "captures")
        self._walls: collections.deque = collections.deque(
            maxlen=cfg.zscore_window)
        self._active_dir: str | None = None
        self._remaining = 0
        self._pending_at = set(cfg.at_step)
        self.captures_taken = 0
        # the most recent capture dir that actually started (active or
        # finished) — the serving engine links a breaching request's trace
        # record to the capture written for it
        self.last_capture_dir: str | None = None
        # fleet cross-process trigger (utils/fleet.py drops the file); the
        # first poll is due immediately — a trigger left while this process
        # was dead must fire on the first post-relaunch step
        self._trigger_path = os.path.join(output_dir, CAPTURE_TRIGGER_NAME)
        self._next_trigger_poll = 0.0

    # -- the three trigger surfaces ---------------------------------------

    def observe_step(self, step: int, wall_s: float | None = None) -> None:
        """Advance the capture window by one step/tick; evaluate the
        at_step and step-time z-score triggers. `wall_s=None` (serve
        ticks) advances the window without feeding the z-score history."""
        was_capturing = self._active_dir is not None
        if was_capturing:
            self._remaining -= 1
            if self._remaining <= 0:
                self._stop()
        self.poll_fleet_trigger(step)
        # at_step semantics are "at or as soon after as possible": a
        # configured step that lands inside an active window (or was
        # skipped while one ran) fires at the first free boundary instead
        # of being silently dropped by an exact-match check
        due = min((s for s in self._pending_at if s <= step), default=None)
        if due is not None and self._active_dir is None:
            self._pending_at.discard(due)
            self.trigger("at_step", step=step)
            return
        if was_capturing or wall_s is None:
            # an in-capture step's wall (the outlier itself) must not
            # poison the rolling baseline
            return
        if (self.cfg.zscore > 0
                and len(self._walls) >= self.cfg.zscore_min_history):
            walls = np.asarray(self._walls, np.float64)
            std = float(walls.std())
            if std > 1e-12:
                z = (wall_s - float(walls.mean())) / std
                if z >= self.cfg.zscore:
                    self.trigger(f"zscore{z:.1f}", step=step)
                    return  # the outlier stays out of the baseline
        self._walls.append(wall_s)

    def poll_fleet_trigger(self, step: int | None = None) -> bool:
        """Consume a fleet-dropped `capture.trigger` in the output dir and
        start a capture for it. Rate-limited (`trigger_poll_s`): steps/
        ticks can run at token rate and a stat per tick would be pure
        overhead. While a capture is already active the file is left in
        place — it fires at the next free boundary instead of vanishing
        into the busy window. Returns True when a capture started."""
        now = time.monotonic()
        if now < self._next_trigger_poll:
            return False
        self._next_trigger_poll = now + max(self.cfg.trigger_poll_s, 0.0)
        if self._active_dir is not None \
                or not os.path.exists(self._trigger_path):
            return False
        try:
            with open(self._trigger_path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            payload = {}
        reason = str((payload or {}).get("alert") or "fleet")
        # consume BEFORE triggering: a retention-capped drop must not
        # leave the file re-firing every poll forever
        try:
            os.unlink(self._trigger_path)
        except OSError:
            pass
        return self.trigger(f"fleet_{reason}", step=step)

    def on_span(self, rec: dict) -> None:
        """Span-stream listener (utils/trace.SpanRecorder.add_listener):
        the numerics observatory's anomaly spans become captures with no
        coupling between the two modules."""
        if self.cfg.on_anomaly and rec.get("name") == "numerics_anomaly":
            self.trigger("numerics_anomaly", step=rec.get("step"))

    def trigger(self, reason: str, step: int | None = None,
                meta: dict | None = None) -> bool:
        """Start a bounded capture now (any trigger surface, including
        serving SLO breaches). Returns True when a capture actually
        started — False while one is active or the retention cap is
        reached. `meta` (e.g. the breaching request's trace id) is written
        as `capture_meta.json` inside the capture dir, so the capture and
        the request-trace waterfall name the same request."""
        if self._active_dir is not None:
            return False
        if self.captures_taken >= self.cfg.max_captures:
            logger.info("profiler capture (%s) skipped: retention cap of "
                        "%d captures reached", reason, self.cfg.max_captures)
            return False
        tag = f"step{step}-{_safe_reason(reason)}" if step is not None \
            else _safe_reason(reason)
        path = os.path.join(self.dir, f"{int(time.time())}-{tag}")
        return self._start(path, reason, step=step, meta=meta)

    # -- capture mechanics --------------------------------------------------

    def _start(self, path: str, reason: str, step: int | None = None,
               meta: dict | None = None) -> bool:
        try:
            import jax

            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
        except Exception as e:
            # an already-running trace (profile_steps window) or a backend
            # without profiling support must never kill the loop
            logger.warning("profiler capture (%s) could not start: %r",
                           reason, e)
            return False
        self._active_dir = path
        self.last_capture_dir = path
        self._remaining = self.cfg.window_steps
        self.captures_taken += 1
        try:
            record = {"reason": reason, "time": time.time()}
            if step is not None:
                record["step"] = step
            if meta:
                record.update(meta)
            with open(os.path.join(path, "capture_meta.json"), "w") as f:
                json.dump(record, f, indent=2)
        except OSError:  # the trace is the payload; meta is best-effort
            logger.exception("capture_meta.json write failed (%s)", path)
        logger.warning("profiler capture started (%s): %s — %d step(s)",
                       reason, path, self.cfg.window_steps)
        return True

    def _stop(self) -> None:
        path, self._active_dir = self._active_dir, None
        if path is None:
            return
        try:
            import jax

            jax.profiler.stop_trace()
            logger.info("profiler capture written: %s (summarize with "
                        "tools/trace_summary.py)", path)
        except Exception:
            logger.exception("profiler capture stop failed (%s)", path)

    @property
    def capturing(self) -> bool:
        return self._active_dir is not None

    def close(self) -> None:
        """Finalize an open window (loop exit on any path)."""
        self._stop()

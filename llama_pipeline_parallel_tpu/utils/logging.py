"""Process-aware logging.

Re-specifies the absent `general_util.logger.get_child_logger` the reference
imports (reference data/data_utils.py:9 — module missing from the extract).
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s %(levelname).1s [%(name)s] %(message)s"
_configured = False


def _configure_root() -> None:
    global _configured
    if _configured:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%H:%M:%S"))
    root = logging.getLogger("llama_pipeline_parallel_tpu")
    root.addHandler(handler)
    root.setLevel(os.environ.get("LPP_TPU_LOG_LEVEL", "INFO").upper())
    root.propagate = False
    _configured = True


def get_logger(name: str) -> logging.Logger:
    _configure_root()
    if not name.startswith("llama_pipeline_parallel_tpu"):
        name = f"llama_pipeline_parallel_tpu.{name}"
    return logging.getLogger(name)


def is_main_process() -> bool:
    import jax

    return jax.process_index() == 0

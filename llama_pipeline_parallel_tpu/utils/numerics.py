"""Numerics observatory: per-stage training-dynamics telemetry.

PR 1 answered *where wall-clock goes* (utils/trace.py) and PR 2 made
crashes survivable (utils/faults.py + ckpt integrity); this layer answers
*what the optimization is doing* — the question the reference punted to
wandb eyeballing of a single scalar (reference trainer_base_ds_mp.py:360-374):
a loss spike, a silently exploding pipeline stage, or a NaN born in one
microbatch used to surface only as a bad `loss` many steps later.

Three cooperating pieces:

- **In-graph statistics** (`step_stats`, plus the activation stats the
  pipeline schedules accumulate per stage — parallel/pipeline.py): cheap
  norm/absmax/rms reductions computed ON DEVICE inside the jitted step from
  the stage-stacked trees (layer leaves are `[num_stages, k, ...]`, so a
  per-stage reduction is one axis-preserving `sum`/`max` — no gather, no
  reshape). Nothing here ever moves host→device: the only traffic is the
  stats' device→host fetch, which `NumericsMonitor` starts asynchronously
  (`copy_to_host_async`) and reads one step later, so the dispatch pipeline
  never stalls on a D2H sync.
- **Nonfinite guard**: the fused train step computes an all-leaves finite
  flag and `jnp.where`-selects the OLD params/opt-state when gradients are
  nonfinite — the update is skipped the same step, in-graph, mirroring fp16
  loss-scaler skip semantics (the reference's fp16 `overflow` path; bf16
  needs no loss scale but still deserves the skip). The host-offload path
  (`optim/offload.py`, `skip_nonfinite`) does the same from the already-
  computed global norm. `halt_on_nonfinite` escalates a skip to a
  `NonfiniteHaltError` that the trainer turns into a final checkpoint
  (the PR 2 commit path) + nonzero exit, so a supervisor's crash-loop
  budget sees a short, clean abort instead of hours of NaN steps.
- **Host-side anomaly detection** (`AnomalyDetector`, `NumericsMonitor`):
  rolling-window z-scores on loss and global grad norm. Every step appends
  one record to `<output_dir>/numerics.jsonl` (process 0, next to
  spans.jsonl); an anomaly additionally emits a `numerics_anomaly` span
  into the PR 1 trace stream and dumps the full per-layer snapshot to
  `numerics-snapshot-<step>.json`. Counters (`nonfinite_steps`,
  `anomaly_count`) surface on the metrics line and in health.json.

`tools/numerics_report.py` renders the offline view: per-stage norm
trajectories, the anomaly timeline, and first-nonfinite localization to a
stage/layer-group.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
import os
import time
from typing import Any

from llama_pipeline_parallel_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# stats fields whose per-step jsonl record keeps the full per-stage vector;
# everything else in the device stats tree is snapshot-only detail. The
# *_per_chunk fields ([num_stages, virtual_stages] nested lists) exist only
# under `schedule: interleaved_1f1b`, where each stage's activations are
# resolved per virtual chunk (parallel/pipeline.py).
PER_STAGE_FIELDS = ("grad_norm_per_stage", "param_norm_per_stage",
                    "update_norm_per_stage", "act_rms_per_stage",
                    "act_absmax_per_stage", "act_rms_per_chunk",
                    "act_absmax_per_chunk")


class NonfiniteHaltError(RuntimeError):
    """Raised by the monitor when `halt_on_nonfinite` is set and a step's
    gradients were nonfinite. Carries the step so the trainer can cut a
    final checkpoint (the update was skipped, so the saved state is the
    last finite one) before exiting nonzero."""

    def __init__(self, step: int, detail: str = ""):
        super().__init__(
            f"nonfinite gradients at step {step}{': ' + detail if detail else ''}"
            f" — halting (numerics.halt_on_nonfinite)")
        self.step = step


@dataclasses.dataclass(frozen=True)
class NumericsConfig:
    """The `numerics.*` config node (docs/OBSERVABILITY.md)."""

    enabled: bool = True
    # rolling z-score detector: window of recent finite samples, threshold,
    # and the minimum history before any z-score verdict is trusted (early
    # training is legitimately volatile)
    window: int = 50
    zscore: float = 6.0
    min_history: int = 8
    # escalate a nonfinite-grad skip to checkpoint-and-exit-nonzero
    halt_on_nonfinite: bool = False
    # dump the per-layer snapshot json on every anomaly
    snapshot_on_anomaly: bool = True

    @classmethod
    def from_cfg(cls, node: dict | None) -> "NumericsConfig":
        node = dict(node or {})
        unknown = set(node) - {f.name for f in dataclasses.fields(cls)}
        if unknown:
            raise ValueError(f"unknown numerics config keys {sorted(unknown)}; "
                             f"known: {sorted(f.name for f in dataclasses.fields(cls))}")
        return cls(**node)


# ---------------------------------------------------------------------------
# In-graph statistics (called inside the jitted step)
# ---------------------------------------------------------------------------

def _stage_sumsq(tree: Any):
    """Sum of squares per stage over a stage-stacked subtree: every leaf is
    [S, ...]; reduce all trailing axes, add across leaves -> [S] fp32."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree.leaves(tree)
    return sum(jnp.sum(jnp.square(x.astype(jnp.float32)),
                       axis=tuple(range(1, x.ndim))) for x in leaves)


def _tree_finite(tree: Any):
    """Scalar bool: every element of every leaf is finite."""
    import jax
    import jax.numpy as jnp

    flags = [jnp.all(jnp.isfinite(x)) for x in jax.tree.leaves(tree)
             if jnp.issubdtype(x.dtype, jnp.floating)]
    out = flags[0]
    for f in flags[1:]:
        out = out & f
    return out


def _group_absmax(layers: Any) -> dict:
    """abs-max per layer-group of the stacked layers subtree, keeping the
    stage axis: {"attn.wq": [S], ...}. Group names follow the tree paths."""
    import jax
    import jax.numpy as jnp

    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(layers)[0]:
        name = ".".join(str(getattr(k, "key", k)) for k in path)
        out[name] = jnp.max(jnp.abs(leaf.astype(jnp.float32)),
                            axis=tuple(range(1, leaf.ndim)))
    return out


def _layer_absmax(layers: Any):
    """[S, k] grad abs-max across all layer leaves — the per-layer-slot
    localization grid the anomaly snapshot dumps."""
    import jax
    import jax.numpy as jnp

    grids = [jnp.max(jnp.abs(x.astype(jnp.float32)),
                     axis=tuple(range(2, x.ndim)))
             for x in jax.tree.leaves(layers)]
    out = grids[0]
    for g in grids[1:]:
        out = jnp.maximum(out, g)
    return out


def _flatten_chunk_axis(layers: Any, virtual_stages: int) -> Any:
    """Interleaved stacked leaves [S, v, k, ...] -> [S, v*k, ...]: the layer
    SLOT axis becomes chunk-major (slot j is chunk j//k, layer j%k), so
    every per-stage/per-slot reduction below works on either layout."""
    import jax

    if virtual_stages == 1:
        return layers
    return jax.tree.map(
        lambda x: x.reshape((x.shape[0], x.shape[1] * x.shape[2]) + x.shape[3:]),
        layers)


def step_stats(params: Any, grads: Any, updates: Any | None = None,
               virtual_stages: int = 1) -> dict:
    """Per-stage / per-layer-group statistics of one step, computed in-graph.

    `params`/`grads` (and optionally `updates`) are the stage-stacked trees
    (layer leaves [S, k, ...], or [S, v, k, ...] under `virtual_stages` > 1
    — flattened to chunk-major [S, v*k, ...] slots first); all reductions
    preserve the leading stage axis, so every output is an [S] vector, an
    [S, slots] grid, or a scalar — a few hundred floats total, fetched
    asynchronously by the monitor.

    Non-stacked leaves (embed/norm/lm_head) have no stage axis; they get
    scalar absmax entries under `replicated_groups` (the pipeline places
    them on the first/last stage, but their gradients are psum'd across pp
    so a stage attribution would be fiction).
    """
    import jax.numpy as jnp

    grad_layers = _flatten_chunk_axis(grads["layers"], virtual_stages)
    param_layers = _flatten_chunk_axis(params["layers"], virtual_stages)
    stats = {
        "grad_norm_per_stage": jnp.sqrt(_stage_sumsq(grad_layers)),
        "param_norm_per_stage": jnp.sqrt(_stage_sumsq(param_layers)),
        "grad_absmax_per_group": _group_absmax(grad_layers),
        "grad_absmax_per_layer": _layer_absmax(grad_layers),
        "replicated_groups": {
            key: jnp.max(jnp.abs(jnp.asarray(
                grads[key]["embedding"] if key == "embed" else grads[key]
            ).astype(jnp.float32)))
            for key in ("embed", "norm", "lm_head")
        },
        "nonfinite": ~_tree_finite(grads),
    }
    if updates is not None:
        stats["update_norm_per_stage"] = jnp.sqrt(_stage_sumsq(
            _flatten_chunk_axis(updates["layers"], virtual_stages)))
    return stats


def poison_mask(num_stages: int, stage):
    """[S] multiplier: +inf at `stage`, 1.0 elsewhere (stage == -1 -> all
    ones). Multiplying one stage's gradients by it manufactures the exact
    failure the observatory exists to catch — nonfinite values born in one
    pipeline stage — at a chosen, reproducible step (the `grad_nonfinite`
    fault op, utils/faults.py)."""
    import jax.numpy as jnp

    return jnp.where(jnp.arange(num_stages) == stage,
                     jnp.float32(float("inf")), jnp.float32(1.0))


def poison_grads(grads: Any, stage) -> Any:
    """Scale the stacked layer gradients of one stage to +-inf/nan (zeros
    become nan via inf*0 — still nonfinite, which is the point)."""
    import jax

    out = dict(grads)
    out["layers"] = jax.tree.map(
        lambda g: g * poison_mask(g.shape[0], stage).reshape(
            (g.shape[0],) + (1,) * (g.ndim - 1)).astype(g.dtype),
        grads["layers"])
    return out


def fault_stage(verdict: str | None) -> int:
    """Parse a faults.fire() step-site verdict into the stage to poison
    (-1 = no poison this step)."""
    if verdict and verdict.startswith("grad_nonfinite"):
        _, _, stage = verdict.partition(":")
        return int(stage or 0)
    return -1


# ---------------------------------------------------------------------------
# Host-side anomaly detection
# ---------------------------------------------------------------------------

class AnomalyDetector:
    """Rolling-window z-score on one scalar series.

    `push(x)` returns the z-score of x against the PREVIOUS window when the
    detector has enough history, else None; the sample then joins the window
    only if finite (a NaN loss must not poison the baseline that flags the
    next spike). Degenerate windows (near-zero std early in training when
    the series is flat) are floored so a microscopic wiggle is not a
    6-sigma event."""

    def __init__(self, window: int, min_history: int):
        self._buf: collections.deque = collections.deque(maxlen=max(window, 2))
        self._min = max(min_history, 2)

    def push(self, x: float) -> float | None:
        z = None
        if math.isfinite(x) and len(self._buf) >= self._min:
            n = len(self._buf)
            mean = sum(self._buf) / n
            var = sum((v - mean) ** 2 for v in self._buf) / n
            std = max(math.sqrt(var), 1e-6 * max(abs(mean), 1.0), 1e-12)
            z = (x - mean) / std
        if math.isfinite(x):
            self._buf.append(x)
        return z


def _to_py(x: Any) -> Any:
    """Device array / numpy -> plain python (lists/floats/bools) for json.
    Nonfinite floats become the strings json.dumps would reject."""
    import numpy as np

    arr = np.asarray(x)
    if arr.ndim == 0:
        v = arr.item()
        if isinstance(v, float) and not math.isfinite(v):
            return "inf" if v > 0 else ("-inf" if v < 0 else "nan")
        return v
    return [_to_py(v) for v in arr]


class NumericsMonitor:
    """The host half of the observatory: async stat fetch, per-step
    numerics.jsonl records, anomaly spans/snapshots, nonfinite accounting.

    `observe(step, loss, grad_norm, stats)` enqueues the step's DEVICE
    arrays after starting their D2H copies (`copy_to_host_async`) and then
    processes the PREVIOUS step's entry — whose transfer has long landed —
    so the hot loop never blocks on the current step's result. `flush()`
    drains the last pending entry at loop exit.

    Every process runs a monitor (the stats are replicated, so detection —
    and a `halt_on_nonfinite` raise — happens pod-uniformly at the same
    step); only `write=True` (process 0) persists jsonl/snapshots.
    `health_fields` is a live dict handed to the Heartbeat as `extra`, so
    health.json always carries the current counters.
    """

    def __init__(self, output_dir: str, cfg: NumericsConfig,
                 write: bool = True, recorder: Any = None):
        self.cfg = cfg
        self._dir = output_dir
        self._recorder = recorder
        self._f = None
        if write:
            os.makedirs(output_dir, exist_ok=True)
            self._f = open(os.path.join(output_dir, "numerics.jsonl"), "a",
                           buffering=1)
        self._pending: collections.deque = collections.deque()
        self._loss_det = AnomalyDetector(cfg.window, cfg.min_history)
        self._grad_det = AnomalyDetector(cfg.window, cfg.min_history)
        self.nonfinite_steps = 0
        self.anomaly_count = 0
        self.health_fields: dict[str, Any] = {
            "nonfinite_steps": 0, "anomaly_count": 0, "grad_norm": None}

    # -- the per-step path -------------------------------------------------

    def observe(self, step: int, loss: Any, grad_norm: Any,
                stats: dict | None) -> None:
        """Enqueue this step's device values (async D2H) and process the
        previous step's. May raise NonfiniteHaltError (from the PREVIOUS
        step's record) when halt_on_nonfinite is configured."""
        import jax

        entry = (step, loss, grad_norm, stats)
        for leaf in jax.tree.leaves((loss, grad_norm, stats)):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        self._pending.append(entry)
        while len(self._pending) > 1:
            self._process(self._pending.popleft())

    def flush(self) -> None:
        """Drain pending entries (end of loop / before a final save). Raises
        like observe()."""
        while self._pending:
            self._process(self._pending.popleft())

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def scalars(self) -> dict[str, Any]:
        """Counters for the metrics line."""
        return {"nonfinite_steps": self.nonfinite_steps,
                "anomaly_count": self.anomaly_count}

    # -- record construction ----------------------------------------------

    def _process(self, entry: tuple) -> None:
        step, loss, grad_norm, stats = entry
        loss = float(_np_scalar(loss))
        grad_norm = None if grad_norm is None else float(_np_scalar(grad_norm))
        rec: dict[str, Any] = {"step": step, "ts": time.time(),
                               "loss": _finite_or_str(loss),
                               "grad_norm": _finite_or_str(grad_norm)}
        nonfinite = False
        host_stats: dict | None = None
        if stats is not None:
            host_stats = {k: _to_py(v) for k, v in stats.items()
                          if k not in ("grad_absmax_per_group",
                                       "grad_absmax_per_layer",
                                       "replicated_groups")}
            nonfinite = bool(host_stats.pop("nonfinite", False))
            for key in PER_STAGE_FIELDS:
                if key in host_stats:
                    rec[key] = host_stats[key]
            if ("update_norm_per_stage" in rec
                    and "param_norm_per_stage" in rec):
                rec["update_ratio_per_stage"] = [
                    _finite_or_str(u / p if p else 0.0)
                    for u, p in zip(
                        [stat_to_float(v) for v in rec["update_norm_per_stage"]],
                        [stat_to_float(v) for v in rec["param_norm_per_stage"]])]
        if grad_norm is not None and not math.isfinite(grad_norm):
            nonfinite = True
        rec["nonfinite"] = nonfinite

        z_loss = self._loss_det.push(loss)
        z_grad = (self._grad_det.push(grad_norm)
                  if grad_norm is not None else None)
        kinds = []
        if nonfinite:
            kinds.append("nonfinite")
        if z_loss is not None and abs(z_loss) > self.cfg.zscore:
            kinds.append("loss_spike")
        if z_grad is not None and abs(z_grad) > self.cfg.zscore:
            kinds.append("grad_spike")
        if z_loss is not None:
            rec["z_loss"] = round(z_loss, 3)
        if z_grad is not None:
            rec["z_grad"] = round(z_grad, 3)
        if kinds:
            rec["anomaly"] = kinds
            self.anomaly_count += 1
        if nonfinite:
            self.nonfinite_steps += 1
        self.health_fields.update(nonfinite_steps=self.nonfinite_steps,
                                  anomaly_count=self.anomaly_count,
                                  grad_norm=_finite_or_str(grad_norm))

        if self._f is not None:
            self._f.write(json.dumps(rec) + "\n")
        if kinds:
            self._on_anomaly(step, rec, stats, kinds)
        if nonfinite and self.cfg.halt_on_nonfinite:
            raise NonfiniteHaltError(step, detail=",".join(kinds))

    def _on_anomaly(self, step: int, rec: dict, stats: dict | None,
                    kinds: list) -> None:
        logger.warning("numerics anomaly at step %d: %s (z_loss=%s z_grad=%s)",
                       step, ",".join(kinds), rec.get("z_loss"),
                       rec.get("z_grad"))
        if self._recorder is not None:
            # zero-duration marker span into the PR 1 trace stream: the
            # anomaly lines up against data_wait/device_step on the same
            # timeline (not a SPAN_BUCKETS name, so goodput is untouched)
            self._recorder.emit("numerics_anomaly", time.time(), 0.0,
                                step=step, kinds=kinds)
        if self._f is not None and self.cfg.snapshot_on_anomaly and stats:
            snap = {"step": step, "kinds": kinds, "record": rec,
                    "grad_absmax_per_group":
                        {k: _to_py(v) for k, v in
                         stats.get("grad_absmax_per_group", {}).items()},
                    "grad_absmax_per_layer":
                        _to_py(stats["grad_absmax_per_layer"])
                        if "grad_absmax_per_layer" in stats else None,
                    "replicated_groups":
                        {k: _to_py(v) for k, v in
                         stats.get("replicated_groups", {}).items()}}
            path = os.path.join(self._dir, f"numerics-snapshot-{step}.json")
            try:
                with open(path, "w") as f:
                    json.dump(snap, f, indent=2)
            except OSError:  # a full disk must not kill training
                logger.exception("could not write numerics snapshot %s", path)


def _np_scalar(x: Any) -> float:
    import numpy as np

    return float(np.asarray(x))


def stat_to_float(v: Any) -> float:
    """Decode one numerics.jsonl stat value: the writer spells nonfinite
    floats as 'inf'/'-inf'/'nan' (JSON has no representation for them —
    see _finite_or_str, the one encode site this must mirror). The offline
    tools (tools/numerics_report.py) share this decoder."""
    if isinstance(v, str):
        return {"inf": math.inf, "-inf": -math.inf}.get(v, math.nan)
    return float(v)


def _finite_or_str(v: float | None) -> Any:
    if v is None:
        return None
    if isinstance(v, str) or math.isfinite(v):
        return v
    return "inf" if v > 0 else ("-inf" if v < 0 else "nan")

"""Console entry point: `lpt-train --config conf/<name>.yaml [key=value ...]`.

Replaces the reference's Hydra `__main__` shim (reference
trainer_base_ds_mp.py:461-473): overrides accept both `key=value` and
`--key=value` forms. The repo-root `train.py` delegates here so both
`python train.py` and the installed script share one implementation.
"""

from __future__ import annotations

import argparse


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", required=True, help="path to a YAML config")
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. 'cpu' for smoke runs with "
                        "XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    p.add_argument("overrides", nargs="*", help="key=value config overrides")
    args, unknown = p.parse_known_args(argv)
    # torchrun-style `--key=value` flags become overrides too (the reference
    # strips the dashes the same way, trainer_base_ds_mp.py:464-471)
    bad = [u for u in unknown if not (u.startswith("--") and "=" in u)]
    if bad:
        p.error(f"unrecognized arguments: {' '.join(bad)}")
    args.overrides += unknown

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from llama_pipeline_parallel_tpu.train import run_training
    from llama_pipeline_parallel_tpu.utils.config import load_config

    cfg = load_config(args.config, args.overrides)
    summary = run_training(cfg)
    print(f"training done: {summary}")

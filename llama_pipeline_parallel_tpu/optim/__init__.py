from llama_pipeline_parallel_tpu.optim.optimizer import (  # noqa: F401
    OptimizerConfig,
    make_optimizer,
    warmup_decay_schedule,
)

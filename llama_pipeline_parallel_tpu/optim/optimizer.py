"""Optimizer + LR schedule.

Rebuilds the engine-side optimizer surface the reference configures through
its DeepSpeed config dict (reference conf yaml:119-136): AdamW with weight
decay/betas/eps, global-norm gradient clipping, and a WarmupDecayLR schedule
whose total/warmup step counts are injected at runtime by the trainer
(reference trainer_base_ds_mp.py:263-275).

Precision model: params are fp32 master weights (cast to bf16 at use inside
the forward — see models/llama/model.py), gradients arrive fp32, and the
optimizer steps in fp32.  This replaces the reference's fp16 loss-scaling
state machine (conf yaml:137-143) entirely: bf16 on TPU needs no loss scale.
"""

from __future__ import annotations

import dataclasses

import optax


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Hyperparameters of record (reference conf yaml:77-86,122-136)."""

    learning_rate: float = 1e-6
    weight_decay: float = 0.001
    beta1: float = 0.9
    beta2: float = 0.99
    eps: float = 1e-8
    max_grad_norm: float = 5.0
    total_steps: int = 1000
    warmup_steps: int = 50


def warmup_decay_schedule(peak_lr: float, total_steps: int, warmup_steps: int
                          ) -> optax.Schedule:
    """Linear warmup to peak, then linear decay to 0 at total_steps — the
    behavior of DeepSpeed's WarmupDecayLR the reference selects
    (conf yaml:129-135)."""
    if warmup_steps >= total_steps:
        raise ValueError(f"warmup_steps ({warmup_steps}) must be < total_steps ({total_steps})")
    return optax.join_schedules(
        [
            optax.linear_schedule(0.0, peak_lr, max(warmup_steps, 1)),
            optax.linear_schedule(peak_lr, 0.0, total_steps - warmup_steps),
        ],
        boundaries=[warmup_steps],
    )


def make_optimizer(cfg: OptimizerConfig) -> tuple[optax.GradientTransformation, optax.Schedule]:
    """AdamW + clip + schedule. Returns (transform, schedule) — the schedule is
    also returned standalone so the trainer can log lr (the reference queries
    `scheduler.get_lr()[0]`, trainer_base_ds_mp.py:362)."""
    schedule = warmup_decay_schedule(cfg.learning_rate, cfg.total_steps, cfg.warmup_steps)
    tx = optax.chain(
        optax.clip_by_global_norm(cfg.max_grad_norm),
        optax.adamw(
            learning_rate=schedule,
            b1=cfg.beta1,
            b2=cfg.beta2,
            eps=cfg.eps,
            weight_decay=cfg.weight_decay,
        ),
    )
    return tx, schedule

"""Host-offloaded AdamW: optimizer state in host DRAM, stepped by native code.

Replaces the reference's ZeRO-offload arrangement (`offload_optimizer:
device: cpu, pin_memory: True` + DeepSpeedCPUAdam, reference conf
yaml:160-162, README.md:70-71 — the "~800 GB host RAM for 65B" path): on a
TPU-VM the fp32 master params and Adam moments stay in host DRAM, the device
holds only the bf16 working copy, and each step moves grads D2H and fresh
bf16 params H2D. Unlike the reference, bf16 compute works WITH offload —
there is no fp16 loss-scale state machine to conflict with it (reference
README.md:133-139 documents that incompatibility).

The update kernel is C++ (csrc/host_adamw.cpp), compiled on first use with
the system g++ and bound via ctypes — no pybind11 dependency. A pure-numpy
fallback keeps the path alive where no compiler exists.
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
import subprocess
import tempfile
from typing import Any

import numpy as np

from llama_pipeline_parallel_tpu.optim.optimizer import OptimizerConfig, warmup_decay_schedule
from llama_pipeline_parallel_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_CSRC = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                     "csrc", "host_adamw.cpp")
_lib = None
_lib_failed = False


def _load_native():
    """Compile (once) and load the native kernel; None if unavailable."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    try:
        cache_dir = os.path.join(tempfile.gettempdir(), "lpt_native")
        os.makedirs(cache_dir, exist_ok=True)
        so_path = os.path.join(cache_dir, "host_adamw.so")
        src = os.path.abspath(_CSRC)
        if (not os.path.exists(so_path)
                or os.path.getmtime(so_path) < os.path.getmtime(src)):
            cmd = ["g++", "-O3", "-march=native", "-fopenmp-simd", "-shared",
                   "-fPIC", src, "-o", so_path]
            subprocess.run(cmd, check=True, capture_output=True)
            logger.info("compiled host AdamW kernel -> %s", so_path)
        lib = ctypes.CDLL(so_path)
        lib.adamw_step.argtypes = [ctypes.POINTER(ctypes.c_float)] * 3 + [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_int64, ctypes.c_float]
        lib.l2_norm_sq.restype = ctypes.c_double
        lib.l2_norm_sq.argtypes = [ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
        _lib = lib
    except Exception as e:
        logger.warning("native host AdamW unavailable (%r); using numpy fallback", e)
        _lib_failed = True
    return _lib


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _adamw_numpy(p, m, v, g, lr, b1, b2, eps, wd, step, grad_scale):
    g = g * grad_scale
    m *= b1
    m += (1 - b1) * g
    v *= b2
    v += (1 - b2) * g * g
    mhat = m / (1 - b1 ** step)
    vhat = v / (1 - b2 ** step)
    p -= lr * (mhat / (np.sqrt(vhat) + eps) + wd * p)


@dataclasses.dataclass
class HostOffloadAdamW:
    """AdamW with fp32 masters + moments in host DRAM.

    Drives flat fp32 numpy buffers; integrates with jax via
    `update(grad_tree) -> param_tree(bf16-ready)`. Contract mirrors
    optax.adamw(chain clip_by_global_norm) numerics.
    """

    cfg: OptimizerConfig

    def init(self, params_tree: Any) -> None:
        import jax

        leaves, self._treedef = jax.tree_util.tree_flatten(params_tree)
        self._shapes = [np.shape(x) for x in leaves]
        self._params = [np.array(x, np.float32, copy=True, order="C") for x in leaves]
        self._m = [np.zeros_like(p) for p in self._params]
        self._v = [np.zeros_like(p) for p in self._params]
        self.step_count = 0
        self._schedule = warmup_decay_schedule(
            self.cfg.learning_rate, self.cfg.total_steps, self.cfg.warmup_steps)
        self._native = _load_native()

    def load_masters(self, params_tree: Any) -> None:
        """Replace the fp32 masters (warm start / resume)."""
        import jax

        leaves = jax.tree.leaves(params_tree)
        if len(leaves) != len(self._params):
            raise ValueError("params tree does not match")
        self._params = [np.array(x, np.float32, copy=True, order="C") for x in leaves]

    @property
    def params_tree(self) -> Any:
        import jax

        return jax.tree_util.tree_unflatten(self._treedef, self._params)

    def update(self, grads_tree: Any) -> Any:
        """One clipped AdamW step; returns the updated fp32 master tree."""
        import jax

        grads = [np.ascontiguousarray(np.asarray(g, np.float32))
                 for g in jax.tree.leaves(grads_tree)]
        if len(grads) != len(self._params):
            raise ValueError("grad tree does not match param tree")

        # global-norm clip (reference grad clip 5.0, conf yaml:136)
        if self._native is not None:
            norm_sq = sum(self._native.l2_norm_sq(_fptr(g), g.size) for g in grads)
        else:
            norm_sq = sum(float((g.astype(np.float64) ** 2).sum()) for g in grads)
        norm = float(np.sqrt(norm_sq))
        clip = self.cfg.max_grad_norm
        grad_scale = clip / norm if (clip and norm > clip) else 1.0

        self.step_count += 1
        lr = float(self._schedule(self.step_count - 1))
        for p, m, v, g in zip(self._params, self._m, self._v, grads):
            if self._native is not None:
                self._native.adamw_step(
                    _fptr(p), _fptr(m), _fptr(v), _fptr(g), p.size,
                    lr, self.cfg.beta1, self.cfg.beta2, self.cfg.eps,
                    self.cfg.weight_decay, self.step_count, grad_scale)
            else:
                _adamw_numpy(p, m, v, g, lr, self.cfg.beta1, self.cfg.beta2,
                             self.cfg.eps, self.cfg.weight_decay,
                             self.step_count, grad_scale)
        self.last_lr = lr
        self.last_grad_norm = norm
        return self.params_tree

    # -- checkpoint integration ------------------------------------------

    def state_dict(self) -> dict:
        """Moments as params-shaped TREES so the checkpoint engine's canonical
        (topology-agnostic) layout transform applies to them too."""
        import jax

        unflatten = lambda leaves: jax.tree_util.tree_unflatten(self._treedef, leaves)
        return {"m": unflatten(self._m), "v": unflatten(self._v),
                "step_count": np.int64(self.step_count)}

    def load_state_dict(self, state: dict) -> None:
        import jax

        self._m = [np.array(x, np.float32, copy=True, order="C")
                   for x in jax.tree.leaves(state["m"])]
        self._v = [np.array(x, np.float32, copy=True, order="C")
                   for x in jax.tree.leaves(state["v"])]
        self.step_count = int(state["step_count"])

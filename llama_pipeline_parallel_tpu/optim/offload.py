"""Host-offloaded AdamW: optimizer state in host DRAM, stepped by native code.

Replaces the reference's ZeRO-offload arrangement (`offload_optimizer:
device: cpu, pin_memory: True` + DeepSpeedCPUAdam, reference conf
yaml:160-162, README.md:70-71 — the "~800 GB host RAM for 65B" path): on a
TPU-VM the fp32 master params and Adam moments stay in host DRAM, the device
holds only the bf16 working copy, and each step moves grads D2H and fresh
bf16 params H2D. Unlike the reference, bf16 compute works WITH offload —
there is no fp16 loss-scale state machine to conflict with it (reference
README.md:133-139 documents that incompatibility).

Sharding-aware, multi-host capable: masters/moments are stored PER DEVICE
SHARD, mirroring the param arrays' mesh sharding — each process keeps and
updates only the shards its addressable devices hold (a 65B pp=8 run spreads
the ~780 GB of optimizer state across hosts the way the reference's ZeRO-1
offload spreads it across ranks). The global grad norm deduplicates
replicated shards by min-device ownership and sums across processes with one
tiny host allgather. Checkpoint state is assembled into globally-sharded
jax.Arrays, so Orbax writes each host's shards from that host.

Step-time hygiene: grad D2H transfers for ALL shards are started
asynchronously up front and overlap the per-shard kernel work; the device
working copy is cast fp32->bf16 on the HOST (native round-to-nearest-even
kernel), halving H2D bytes vs uploading fp32 and casting on device. Per-phase
timings are kept in `last_timings`.

The update kernel is C++ (csrc/host_adamw.cpp, OpenMP parallel + SIMD),
compiled on first use with the system g++ and bound via ctypes — no pybind11
dependency. A pure-numpy fallback keeps the path alive where no compiler
exists.

This module is the HOST-side tier (python-driven D2H/kernel/H2D around the
step); its IN-GRAPH sibling is `utils/host_stash.py`, which generalizes the
same keep-cold-bytes-in-host-DRAM-behind-overlapped-transfers idea to the
pipeline schedules' residual stores (the zb1 W queue, the stage-input ring
buffer) with `jax.device_put`-to-memory-kind transfers XLA schedules
asynchronously INSIDE the jitted step — see docs/SCHEDULES.md "Host
offload". Measure the link both tiers share with
`host_stash.measure_transfer_bandwidth` (bench.py `extra:offload-bw`).
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
import subprocess
import tempfile
import time
from typing import Any

import numpy as np

from llama_pipeline_parallel_tpu.optim.optimizer import OptimizerConfig, warmup_decay_schedule
from llama_pipeline_parallel_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# inside the package so installed wheels ship the kernel source too
_CSRC = os.path.join(os.path.dirname(__file__), os.pardir,
                     "csrc", "host_adamw.cpp")
_lib = None
_lib_failed = False


def _load_native():
    """Compile (once) and load the native kernel; None if unavailable."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    try:
        cache_dir = os.path.join(tempfile.gettempdir(), "lpt_native")
        os.makedirs(cache_dir, exist_ok=True)
        so_path = os.path.join(cache_dir, "host_adamw.so")
        src = os.path.abspath(_CSRC)
        if (not os.path.exists(so_path)
                or os.path.getmtime(so_path) < os.path.getmtime(src)):
            cmd = ["g++", "-O3", "-march=native", "-fopenmp", "-shared",
                   "-fPIC", src, "-o", so_path]
            subprocess.run(cmd, check=True, capture_output=True)
            logger.info("compiled host AdamW kernel -> %s", so_path)
        lib = ctypes.CDLL(so_path)
        lib.adamw_step.argtypes = [ctypes.POINTER(ctypes.c_float)] * 3 + [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_int64, ctypes.c_float]
        lib.l2_norm_sq.restype = ctypes.c_double
        lib.l2_norm_sq.argtypes = [ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
        lib.f32_to_bf16.argtypes = [ctypes.POINTER(ctypes.c_float),
                                    ctypes.POINTER(ctypes.c_uint16), ctypes.c_int64]
        _lib = lib
    except Exception as e:
        logger.warning("native host AdamW unavailable (%r); using numpy fallback", e)
        _lib_failed = True
    return _lib


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _adamw_numpy(p, m, v, g, lr, b1, b2, eps, wd, step, grad_scale):
    g = g * grad_scale
    m *= b1
    m += (1 - b1) * g
    v *= b2
    v += (1 - b2) * g * g
    mhat = m / (1 - b1 ** step)
    vhat = v / (1 - b2 ** step)
    p -= lr * (mhat / (np.sqrt(vhat) + eps) + wd * p)


def _cast_bf16(src: np.ndarray, native) -> np.ndarray:
    """fp32 -> bf16 numpy array (native RNE kernel, ml_dtypes fallback)."""
    import ml_dtypes

    if native is not None:
        out = np.empty(src.shape, np.uint16)
        native.f32_to_bf16(_fptr(src), out.ctypes.data_as(
            ctypes.POINTER(ctypes.c_uint16)), src.size)
        return out.view(ml_dtypes.bfloat16)
    return src.astype(ml_dtypes.bfloat16)


def _index_key(index: tuple) -> tuple:
    return tuple((s.start, s.stop, s.step) for s in index)


@dataclasses.dataclass
class _Shard:
    """One distinct shard of one param leaf, process-local."""

    index: tuple          # tuple of slices into the global array
    devices: list         # addressable devices holding this shard
    owner: bool           # does THIS process own it for global-norm counting?
    p: np.ndarray         # fp32 master
    m: np.ndarray
    v: np.ndarray


class _Leaf:
    """All process-local shards of one param leaf + its global layout."""

    def __init__(self, x) -> None:
        import jax

        self.global_shape = tuple(x.shape)
        self.sharding = x.sharding
        imap = self.sharding.devices_indices_map(self.global_shape)
        by_key: dict = {}
        for d, index in imap.items():
            by_key.setdefault(_index_key(index), []).append(d)
        local_data = {_index_key(s.index): s.data for s in x.addressable_shards}
        pid = jax.process_index()
        self.shards: dict = {}
        for key, devs in by_key.items():
            local_devs = [d for d in devs if d.process_index == pid]
            if not local_devs:
                continue
            owner_dev = min(devs, key=lambda d: d.id)
            self.shards[key] = _Shard(
                index=tuple(slice(*k) for k in key),
                devices=local_devs,
                owner=owner_dev.process_index == pid,
                p=np.array(local_data[key], np.float32, copy=True, order="C"),
                m=np.zeros(local_data[key].shape, np.float32),
                v=np.zeros(local_data[key].shape, np.float32),
            )
        if not self.shards:
            raise ValueError("process holds no shard of a param leaf — the "
                             "mesh leaves this host without addressable devices")

    def grad_shards(self, g) -> dict:
        """key -> host fp32 grad array for each of this leaf's shard keys.
        Falls back to slicing a full transfer when the grad array's sharding
        does not match the masters' (it does on the trainer path)."""
        avail = {_index_key(s.index): s.data for s in g.addressable_shards}
        out, full = {}, None
        for key, shard in self.shards.items():
            if key in avail:
                out[key] = avail[key]
            else:
                if full is None:
                    full = np.asarray(g, np.float32)
                out[key] = np.ascontiguousarray(full[shard.index])
        return out

    def assemble(self, values: dict) -> Any:
        """Build the globally-sharded jax.Array for this leaf from per-key
        host arrays (this process contributes its addressable shards).

        Fully-replicated leaves (one distinct shard) go through
        `jax.device_put(value, sharding)`, which lets the runtime upload once
        and broadcast. Sharded leaves use per-device puts; when a shard is
        replicated across dp those bytes upload once per local replica — an
        ICI-broadcast optimization left for when multi-chip H2D shows up in
        a profile (single-chip, the bench path, has no replicas)."""
        import jax

        if len(self.shards) == 1:
            (shard,) = self.shards.values()
            covers_all = all(
                (sl.start in (0, None)) and (sl.stop in (dim, None))
                for sl, dim in zip(shard.index, self.global_shape))
            if covers_all:
                return jax.device_put(values[_index_key(shard.index)],
                                      self.sharding)
        arrays = []
        for shard in self.shards.values():
            key = _index_key(shard.index)
            for d in shard.devices:
                arrays.append(jax.device_put(values[key], d))
        return jax.make_array_from_single_device_arrays(
            self.global_shape, self.sharding, arrays)


@dataclasses.dataclass
class HostOffloadAdamW:
    """AdamW with fp32 masters + moments in host DRAM, sharding-aware.

    Contract mirrors optax.adamw (chained with clip_by_global_norm) numerics.
    `update(grad_tree)` steps the masters; `device_params(dtype)` builds the
    bf16 working copy; `masters_tree()`/`state_dict()` expose globally
    sharded fp32 arrays for checkpointing.
    """

    cfg: OptimizerConfig
    # Numerics-observatory skip semantics (utils/numerics.py, mirroring the
    # fused step's in-graph guard): when the global grad norm is nonfinite,
    # leave masters/moments/step-count untouched for this step — the working
    # copy re-uploads unchanged. `last_nonfinite` flags the verdict either
    # way; `nonfinite_count` accumulates skips.
    skip_nonfinite: bool = False
    # Compute the global grad norm ON DEVICE (one fused XLA reduction + a
    # scalar D2H) instead of on the host after the full-tree D2H. The host
    # path must pull EVERY gradient byte down before the first AdamW can run
    # (the global clip factor depends on all of them — the SURVEY §7.3-item-3
    # serialization); with the scalar known up front, the fused step streams
    # leaf-by-leaf — wait-for-leaf-i, update-i, cast-i, upload-i — so later
    # leaves' wire time hides behind earlier leaves' host compute. Numerics:
    # fp32 accumulation, exactly optax.clip_by_global_norm's math (the host
    # path accumulates in fp64, so the clip factor can differ in the last
    # ulps — opt-in, and update() always keeps the host path).
    device_norm: bool = False

    def init(self, params_tree: Any) -> None:
        import jax

        leaves, self._treedef = jax.tree_util.tree_flatten(params_tree)
        self._leaves = [_Leaf(x) for x in leaves]
        self.step_count = 0
        self._schedule = warmup_decay_schedule(
            self.cfg.learning_rate, self.cfg.total_steps, self.cfg.warmup_steps)
        self._native = _load_native()
        self._norm_sq_jit = None
        self.last_timings: dict = {}
        self.last_nonfinite = False
        self.nonfinite_count = 0

    # -- master access ----------------------------------------------------

    def _check_tree(self, tree: Any) -> list:
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if treedef != self._treedef or len(leaves) != len(self._leaves):
            raise ValueError("tree does not match the initialized param tree")
        return leaves

    def load_masters(self, params_tree: Any) -> None:
        """Replace the fp32 masters (warm start / resume)."""
        for leaf, x in zip(self._leaves, self._check_tree(params_tree)):
            self._scatter(leaf, x, "p")

    def _scatter(self, leaf: _Leaf, x, attr: str) -> None:
        """Load a (global jax.Array or host numpy) value into leaf shards."""
        shard_data = ({_index_key(s.index): s.data for s in x.addressable_shards}
                      if hasattr(x, "addressable_shards") else None)
        for key, shard in leaf.shards.items():
            if shard_data is not None and key in shard_data:
                val = shard_data[key]
            else:
                val = np.asarray(x)[shard.index]
            setattr(shard, attr,
                    np.array(val, np.float32, copy=True, order="C"))

    def masters_tree(self) -> Any:
        """fp32 masters as globally-sharded jax.Arrays (checkpoint input)."""
        import jax

        vals = [leaf.assemble({_index_key(s.index): s.p
                               for s in leaf.shards.values()})
                for leaf in self._leaves]
        return jax.tree_util.tree_unflatten(self._treedef, vals)

    def abstract_tree(self) -> Any:
        """ShapeDtypeStruct tree of the fp32 masters WITH their mesh
        shardings — the restore template that keeps checkpoint loads sharded
        (no leaf ever funnels through a single device)."""
        import jax

        vals = [jax.ShapeDtypeStruct(leaf.global_shape, np.float32,
                                     sharding=leaf.sharding)
                for leaf in self._leaves]
        return jax.tree_util.tree_unflatten(self._treedef, vals)

    def moments_tree(self, attr: str) -> Any:
        """One moment tree ("m" or "v") as globally-sharded jax.Arrays —
        assembled alone so the checkpoint path can stream p/m/v one at a
        time instead of materializing 12 bytes/param on device at once."""
        import jax

        vals = [leaf.assemble({_index_key(s.index): getattr(s, attr)
                               for s in leaf.shards.values()})
                for leaf in self._leaves]
        return jax.tree_util.tree_unflatten(self._treedef, vals)


    def _cast_working(self, p: np.ndarray, dtype) -> np.ndarray:
        """fp32 master -> working-copy dtype, on the HOST (bf16 via the
        native RNE kernel halves H2D bytes vs uploading fp32). The ONE cast
        policy for both the standalone and the fused step paths; always
        allocates a fresh buffer, so uploads never alias the mutable
        masters."""
        import jax.numpy as jnp

        if jnp.dtype(dtype) == jnp.dtype(jnp.bfloat16):
            return _cast_bf16(p, self._native)
        return p.astype(dtype)

    def device_params(self, dtype=None) -> Any:
        """The bf16 (or `dtype`) device working copy, cast on the HOST so the
        H2D transfer moves half the bytes of an fp32 upload."""
        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        dtype = dtype or jnp.bfloat16
        vals = []
        for leaf in self._leaves:
            cast = {_index_key(s.index): self._cast_working(s.p, dtype)
                    for s in leaf.shards.values()}
            vals.append(leaf.assemble(cast))
        # Cast + transfer DISPATCH only: device_put returns after enqueueing,
        # so the wire time is absorbed by the next dispatched computation
        # (blocking here would serialize away exactly the overlap we want).
        self.last_timings["h2d_dispatch_ms"] = 1000 * (time.perf_counter() - t0)
        return jax.tree_util.tree_unflatten(self._treedef, vals)

    # -- the step ---------------------------------------------------------

    def _gather_grads_and_norm(self, glvs: list) -> tuple[list, float, float]:
        """D2H every grad shard + the clipped-AdamW scale factors.

        All transfers start first (they overlap each other); each leaf's
        norm-square kernel then runs as soon as ITS transfer lands, hiding
        later leaves' wire time behind earlier leaves' norm compute. The
        global norm deduplicates replicated shards by min-device ownership
        and sums across processes with one tiny host allgather.
        Returns (per-leaf grad dicts, lr, grad_scale)."""
        import jax

        for g in glvs:
            if hasattr(g, "copy_to_host_async"):
                g.copy_to_host_async()
        grad_np: list[dict] = []
        norm_sq = 0.0
        for leaf, g in zip(self._leaves, glvs):
            shards = leaf.grad_shards(g)
            gnp = {k: np.ascontiguousarray(np.asarray(v, np.float32))
                   for k, v in shards.items()}
            grad_np.append(gnp)
            for key, shard in leaf.shards.items():
                if not shard.owner:
                    continue
                gs = gnp[key]
                if self._native is not None:
                    norm_sq += self._native.l2_norm_sq(_fptr(gs), gs.size)
                else:
                    norm_sq += float((gs.astype(np.float64) ** 2).sum())
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            norm_sq = float(multihost_utils.process_allgather(
                np.asarray(norm_sq, np.float64)).sum())
        lr, grad_scale = self._clip_and_advance(float(np.sqrt(norm_sq)))
        return grad_np, lr, grad_scale

    def _clip_and_advance(self, norm: float) -> tuple[float, float]:
        """Shared epilogue of both norm paths: clip factor from the global
        norm, step count, lr sample, telemetry. A nonfinite norm under
        `skip_nonfinite` advances NOTHING (no step count, no moments later —
        the apply loops check `last_nonfinite`), matching the fused step's
        in-graph where-skip."""
        import math

        self.last_nonfinite = not math.isfinite(norm)
        self.last_grad_norm = norm
        if self.last_nonfinite and self.skip_nonfinite:
            self.nonfinite_count += 1
            self.last_lr = float(self._schedule(self.step_count))
            logger.warning("nonfinite global grad norm (%r); skipping the "
                           "optimizer step (%d skipped so far)", norm,
                           self.nonfinite_count)
            return self.last_lr, 0.0
        clip = self.cfg.max_grad_norm
        grad_scale = clip / norm if (clip and norm > clip) else 1.0
        self.step_count += 1
        lr = float(self._schedule(self.step_count - 1))
        self.last_lr = lr
        return lr, grad_scale

    def _skip_this_step(self) -> bool:
        return self.skip_nonfinite and self.last_nonfinite

    def _apply_shard(self, shard: _Shard, g: np.ndarray, lr: float,
                     grad_scale: float) -> None:
        if self._native is not None:
            self._native.adamw_step(
                _fptr(shard.p), _fptr(shard.m), _fptr(shard.v),
                _fptr(g), shard.p.size,
                lr, self.cfg.beta1, self.cfg.beta2, self.cfg.eps,
                self.cfg.weight_decay, self.step_count, grad_scale)
        else:
            _adamw_numpy(shard.p, shard.m, shard.v, g, lr,
                         self.cfg.beta1, self.cfg.beta2, self.cfg.eps,
                         self.cfg.weight_decay, self.step_count, grad_scale)

    def update(self, grads_tree: Any) -> None:
        """One clipped AdamW step on every process-local shard."""
        t0 = time.perf_counter()
        grad_np, lr, grad_scale = self._gather_grads_and_norm(
            self._check_tree(grads_tree))
        t1 = time.perf_counter()
        if not self._skip_this_step():
            for leaf, gnp in zip(self._leaves, grad_np):
                for key, shard in leaf.shards.items():
                    self._apply_shard(shard, gnp[key], lr, grad_scale)
        t2 = time.perf_counter()
        # fresh dict: a stale phase key from the OTHER step path must not
        # linger in the metrics stream (d2h_norm_ms covers transfers AND the
        # norm/allgather — the norm kernels overlap the transfer tail)
        self.last_timings = {"d2h_norm_ms": 1000 * (t1 - t0),
                             "update_ms": 1000 * (t2 - t1)}

    def _norm_sq_and_step(self, glvs: list) -> tuple[float, float]:
        """Device-side global grad norm: one fused fp32 reduction (exactly
        optax.clip_by_global_norm's accumulation) whose replicated scalar is
        the only thing the host blocks on — dispatched BEFORE the per-leaf
        D2H stream so it lands while the leaves are still on the wire. Under
        multi-process, GSPMD inserts the cross-host reduction; every process
        calls this every step, so the collective stays uniform. Returns
        (lr, grad_scale) and advances the step count."""
        import jax
        import jax.numpy as jnp

        if self._norm_sq_jit is None:
            # accumulate in fp32 regardless of grad dtype (gpipe grads can
            # arrive bf16): a bf16 norm carries ~8 mantissa bits — wrong
            # clipping decisions near the threshold
            self._norm_sq_jit = jax.jit(
                lambda gs: sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                               for g in gs))
        norm_sq_dev = self._norm_sq_jit(glvs)
        for g in glvs:
            g.copy_to_host_async()
        return self._clip_and_advance(float(jnp.sqrt(norm_sq_dev)))

    def update_and_refresh(self, grads_tree: Any, dtype=None) -> Any:
        """One clipped AdamW step AND the fresh device working copy, software-
        pipelined per leaf: leaf i's bf16 cast + H2D upload are dispatched
        the moment its shards are stepped, so the wire time of leaf i
        overlaps leaf i+1's AdamW kernel instead of waiting for the whole
        update (the SURVEY §7.3-item-3 stall: a serial
        update-everything-then-upload-everything step leaves the device idle
        for the full sum of both phases).

        With `device_norm` (the trainer's default) the full-tree D2H barrier
        goes too: the clip factor comes from a device-side reduction, so the
        loop additionally overlaps leaf i+1's DOWNLOAD with leaf i's AdamW —
        end-to-end streaming, phase keys norm_ms / stream_d2h_update_h2d_ms.
        Otherwise numerics are identical to `update()` + `device_params()` —
        same kernels, same order.

        Safe against in-place master mutation: each upload reads a freshly
        allocated cast buffer, never `shard.p` itself."""
        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        glvs = self._check_tree(grads_tree)
        streaming = self.device_norm and all(
            hasattr(g, "copy_to_host_async") for g in glvs)
        if streaming:
            lr, grad_scale = self._norm_sq_and_step(glvs)
            grad_np = None
        else:
            grad_np, lr, grad_scale = self._gather_grads_and_norm(glvs)
        t1 = time.perf_counter()
        dtype = dtype or jnp.bfloat16
        vals = []
        for i, (leaf, g) in enumerate(zip(self._leaves, glvs)):
            # streaming: block on THIS leaf's transfer only (later leaves
            # keep landing while this one updates)
            gnp = (grad_np[i] if grad_np is not None else
                   {k: np.ascontiguousarray(np.asarray(v, np.float32))
                    for k, v in leaf.grad_shards(g).items()})
            cast = {}
            for key, shard in leaf.shards.items():
                if not self._skip_this_step():
                    self._apply_shard(shard, gnp[key], lr, grad_scale)
                cast[key] = self._cast_working(shard.p, dtype)
            # assemble dispatches this leaf's H2D asynchronously; the next
            # leaf's AdamW kernels run while these bytes are on the wire
            vals.append(leaf.assemble(cast))
        t2 = time.perf_counter()
        # fresh dict: no stale keys from the other step paths
        if streaming:
            self.last_timings = {"norm_ms": 1000 * (t1 - t0),
                                 "stream_d2h_update_h2d_ms": 1000 * (t2 - t1)}
        else:
            self.last_timings = {"d2h_norm_ms": 1000 * (t1 - t0),
                                 "update_h2d_ms": 1000 * (t2 - t1)}
        return jax.tree_util.tree_unflatten(self._treedef, vals)

    # -- checkpoint integration ------------------------------------------

    def state_dict(self) -> dict:
        """Moments as params-shaped TREES of globally-sharded arrays so the
        checkpoint engine's canonical (topology-agnostic) layout transform
        applies to them too."""
        return {"m": self.moments_tree("m"), "v": self.moments_tree("v"),
                "step_count": np.int64(self.step_count)}

    def load_state_dict(self, state: dict) -> None:
        for leaf, x in zip(self._leaves, self._check_tree(state["m"])):
            self._scatter(leaf, x, "m")
        for leaf, x in zip(self._leaves, self._check_tree(state["v"])):
            self._scatter(leaf, x, "v")
        self.step_count = int(state["step_count"])

"""HF <-> native parameter-tree conversion.

The in-memory half of the converter (reference convert2ckpt.py:19-48 walks an
HF `LlamaForCausalLM` state_dict into per-layer DeepSpeed files). Here the HF
state_dict maps into the stacked pytree of model.py; tools/convert_hf.py wraps
this with checkpoint I/O.

torch Linear stores weights [out, in] and computes y = x @ W.T; our matmuls are
y = x @ W with W [in, out], so every projection transposes on import.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig


def _np(t: Any) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().to("cpu").float().numpy()
    return np.asarray(t, dtype=np.float32)


def params_from_hf_state_dict(sd: Mapping[str, Any], cfg: LlamaConfig) -> dict:
    """Build the stacked params pytree from an HF LlamaForCausalLM state_dict."""
    n = cfg.num_hidden_layers

    def layer_stack(fmt: str, transpose: bool) -> np.ndarray:
        mats = []
        for i in range(n):
            w = _np(sd[fmt.format(i=i)])
            mats.append(w.T if transpose else w)
        return np.stack(mats)

    params = {
        "embed": {"embedding": _np(sd["model.embed_tokens.weight"])},
        "layers": {
            "attn": {
                "wq": layer_stack("model.layers.{i}.self_attn.q_proj.weight", True),
                "wk": layer_stack("model.layers.{i}.self_attn.k_proj.weight", True),
                "wv": layer_stack("model.layers.{i}.self_attn.v_proj.weight", True),
                "wo": layer_stack("model.layers.{i}.self_attn.o_proj.weight", True),
            },
            "mlp": {
                "gate": layer_stack("model.layers.{i}.mlp.gate_proj.weight", True),
                "up": layer_stack("model.layers.{i}.mlp.up_proj.weight", True),
                "down": layer_stack("model.layers.{i}.mlp.down_proj.weight", True),
            },
            "input_norm": layer_stack("model.layers.{i}.input_layernorm.weight", False),
            "post_norm": layer_stack("model.layers.{i}.post_attention_layernorm.weight", False),
        },
        "norm": _np(sd["model.norm.weight"]),
    }
    if cfg.tie_word_embeddings:
        params["lm_head"] = params["embed"]["embedding"].T.copy()
    elif "lm_head.weight" not in sd:
        raise KeyError(
            "state_dict has no 'lm_head.weight' but tie_word_embeddings=False; "
            "refusing to silently tie (LLaMA must not tie, reference README.md:44-46)")
    else:
        params["lm_head"] = _np(sd["lm_head.weight"]).T.copy()
    return params


def hf_state_dict_from_params(params: dict, cfg: LlamaConfig) -> dict[str, np.ndarray]:
    """Inverse mapping (native -> HF names), for round-trip export/tests."""
    out: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["embed"]["embedding"], np.float32),
        "model.norm.weight": np.asarray(params["norm"], np.float32),
        "lm_head.weight": np.asarray(params["lm_head"], np.float32).T.copy(),
    }
    layers = params["layers"]
    names = {
        "self_attn.q_proj.weight": (layers["attn"]["wq"], True),
        "self_attn.k_proj.weight": (layers["attn"]["wk"], True),
        "self_attn.v_proj.weight": (layers["attn"]["wv"], True),
        "self_attn.o_proj.weight": (layers["attn"]["wo"], True),
        "mlp.gate_proj.weight": (layers["mlp"]["gate"], True),
        "mlp.up_proj.weight": (layers["mlp"]["up"], True),
        "mlp.down_proj.weight": (layers["mlp"]["down"], True),
        "input_layernorm.weight": (layers["input_norm"], False),
        "post_attention_layernorm.weight": (layers["post_norm"], False),
    }
    for i in range(cfg.num_hidden_layers):
        for suffix, (stacked, transpose) in names.items():
            w = np.asarray(stacked[i], np.float32)
            out[f"model.layers.{i}.{suffix}"] = w.T.copy() if transpose else w
    return out

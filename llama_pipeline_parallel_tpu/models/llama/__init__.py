from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig  # noqa: F401
from llama_pipeline_parallel_tpu.models.llama.decode import (  # noqa: F401
    GenerationConfig,
    generate,
)
from llama_pipeline_parallel_tpu.models.llama.model import (  # noqa: F401
    forward,
    init_params,
    loss_fn,
)

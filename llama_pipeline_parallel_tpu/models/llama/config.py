"""LLaMA model configuration.

Replaces the reference's use of `transformers.AutoConfig` as the model factory
(reference trainer_base_ds_mp.py:422, conf yaml `model:` node with `_target_:
transformers.AutoConfig.from_pretrained`): a typed dataclass with presets for
the model family the reference targets (LLaMA-7B/13B/65B, CodeLlama-34B-16k).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int | None = None  # GQA; None -> MHA
    max_position_embeddings: int = 2048
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False  # LLaMA must NOT tie (reference README.md:44-46)
    # compute dtype for activations; params are kept fp32 master and cast at entry
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    def __post_init__(self) -> None:
        if self.hidden_size % self.num_attention_heads:
            raise ValueError(
                f"hidden_size ({self.hidden_size}) must be a multiple of "
                f"num_attention_heads ({self.num_attention_heads})")
        if self.num_key_value_heads is not None and self.num_key_value_heads < 1:
            raise ValueError(f"num_key_value_heads must be >= 1, got {self.num_key_value_heads}")
        if self.num_attention_heads % self.kv_heads:
            raise ValueError("num_attention_heads must be a multiple of num_key_value_heads")

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def kv_heads(self) -> int:
        if self.num_key_value_heads is None:
            return self.num_attention_heads
        return self.num_key_value_heads

    # ---- presets -----------------------------------------------------------

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        """4-layer toy model for tests (SURVEY.md §7.2 minimum slice)."""
        base = dict(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=128, dtype=jnp.float32,
        )
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def llama_7b(**kw) -> "LlamaConfig":
        return LlamaConfig(**kw)

    @staticmethod
    def llama_13b(**kw) -> "LlamaConfig":
        base = dict(hidden_size=5120, intermediate_size=13824,
                    num_hidden_layers=40, num_attention_heads=40)
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def llama_33b(**kw) -> "LlamaConfig":
        base = dict(hidden_size=6656, intermediate_size=17920,
                    num_hidden_layers=60, num_attention_heads=52)
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def codellama_34b_16k(**kw) -> "LlamaConfig":
        base = dict(hidden_size=8192, intermediate_size=22016,
                    num_hidden_layers=48, num_attention_heads=64,
                    num_key_value_heads=8, max_position_embeddings=16384,
                    rope_theta=1000000.0)
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def llama_65b(**kw) -> "LlamaConfig":
        base = dict(hidden_size=8192, intermediate_size=22016,
                    num_hidden_layers=80, num_attention_heads=64)
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def llama2_7b(**kw) -> "LlamaConfig":
        """Llama-2 generation: 4k context (GQA only on the 70B size)."""
        base = dict(max_position_embeddings=4096)
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def llama2_13b(**kw) -> "LlamaConfig":
        base = dict(hidden_size=5120, intermediate_size=13824,
                    num_hidden_layers=40, num_attention_heads=40,
                    max_position_embeddings=4096)
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def llama2_70b(**kw) -> "LlamaConfig":
        """GQA flagship: 8 kv heads over 64 query heads — exercises the
        grouped-KV path (repeat_kv / flash GQA / tp kv constraints) at its
        production shape."""
        base = dict(hidden_size=8192, intermediate_size=28672,
                    num_hidden_layers=80, num_attention_heads=64,
                    num_key_value_heads=8, max_position_embeddings=4096)
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def from_hf_config(hf_config: Any, **kw) -> "LlamaConfig":
        """Build from a `transformers.LlamaConfig` (the converter entry point,
        replacing reference convert2ckpt.py:56)."""
        base = dict(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_hidden_layers=hf_config.num_hidden_layers,
            num_attention_heads=hf_config.num_attention_heads,
            num_key_value_heads=getattr(hf_config, "num_key_value_heads", None),
            max_position_embeddings=hf_config.max_position_embeddings,
            rms_norm_eps=hf_config.rms_norm_eps,
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings", False),
        )
        base.update(kw)
        return LlamaConfig(**base)

    def flops_per_token(self) -> float:
        """Approximate training FLOPs per token (fwd+bwd), for MFU accounting."""
        d, f, L, V = (self.hidden_size, self.intermediate_size,
                      self.num_hidden_layers, self.vocab_size)
        kv_ratio = self.kv_heads / self.num_attention_heads
        per_layer = 2 * d * d * (2 + 2 * kv_ratio) + 2 * 3 * d * f
        embed_head = 2 * d * V
        return 3 * (L * per_layer + embed_head)

"""Layer -> pipeline-stage manifest.

The reference encodes the stage partition twice, implicitly: once as layer-list
order (models/llama_ds_mp_wrap.py:213-219) and once as checkpoint filename
arithmetic (convert2ckpt.py:24-36, `layer_{i+1:02d}-model_00-...`), and the
two must stay in lockstep by convention. Here the mapping is one explicit,
serializable object that both the pipeline runtime and the checkpoint engine
consume — which is also what makes PP-topology-changing restores possible
(SURVEY.md §7.3 item 5).

Uneven partitions (reference `LayerSpec` lists admit them,
models/llama_ds_mp_wrap.py:209-224; SURVEY.md §7.3 item 2 makes them the
stage-balance lever): `layer_counts` assigns each stage its own layer count.
The stacked runtime layout pads every stage to `max_layers_per_stage` slots;
padded slots hold ZERO weights, which makes the residual decoder block an
exact identity (all projection outputs vanish) with identically zero
gradients — a fixed point of AdamW — so correctness never depends on the
padding being skipped. The pipeline additionally cond-skips padded slots
when no collective lives inside the layer (parallel/pipeline.py).
"""

from __future__ import annotations

import dataclasses
import json

from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig


@dataclasses.dataclass(frozen=True)
class StageManifest:
    num_layers: int
    num_stages: int
    # None -> even split (num_layers % num_stages must be 0). Otherwise one
    # count per stage, each >= 1, summing to num_layers.
    layer_counts: tuple | None = None
    # Interleaved scheduling (schedule: interleaved_1f1b or zb1): each stage
    # owns `virtual_stages` NON-CONTIGUOUS chunks of layers, assigned
    # round-robin over global chunks — chunk c (of num_stages *
    # virtual_stages equal chunks, in layer order) lives on stage
    # c % num_stages as its virtual chunk c // num_stages, so the activation
    # ring passes through every stage `virtual_stages` times per microbatch.
    # 1 = the flat contiguous partition (every existing checkpoint/manifest
    # deserializes to it). The manifest is SCHEDULE-AGNOSTIC on disk: the
    # canonical [num_layers, ...] checkpoint layout never changes, so any
    # PR-2/PR-5 checkpoint restores into flat, interleaved, or zb1 layouts
    # through the same stack_stages/unstack_stages pair.
    virtual_stages: int = 1

    def __post_init__(self) -> None:
        if self.num_stages < 1:
            raise ValueError(f"num_stages must be >= 1, got {self.num_stages}")
        if self.virtual_stages < 1:
            raise ValueError(
                f"virtual_stages must be >= 1, got {self.virtual_stages}")
        if self.virtual_stages > 1:
            if self.layer_counts is not None:
                raise ValueError(
                    "virtual_stages > 1 requires an even partition: the "
                    "round-robin chunk assignment has no uneven form — drop "
                    "layer_counts or set virtual_stages: 1")
            denom = self.num_stages * self.virtual_stages
            if self.num_layers % denom:
                raise ValueError(
                    f"num_layers={self.num_layers} not divisible by "
                    f"num_stages*virtual_stages={denom}; interleaved "
                    f"scheduling needs equal-size chunks")
        if self.layer_counts is None:
            if self.num_layers % self.num_stages:
                raise ValueError(
                    f"num_layers={self.num_layers} not divisible by "
                    f"num_stages={self.num_stages}; pass layer_counts for an "
                    f"uneven partition (or use StageManifest.balanced)")
        else:
            counts = tuple(int(c) for c in self.layer_counts)
            object.__setattr__(self, "layer_counts", counts)
            if len(counts) != self.num_stages:
                raise ValueError(
                    f"layer_counts has {len(counts)} entries for "
                    f"num_stages={self.num_stages}")
            if any(c < 1 for c in counts):
                raise ValueError(f"every stage needs >= 1 layer, got {counts}")
            if sum(counts) != self.num_layers:
                raise ValueError(
                    f"layer_counts {counts} sum to {sum(counts)}, expected "
                    f"num_layers={self.num_layers}")

    @property
    def is_even(self) -> bool:
        return (self.layer_counts is None
                or len(set(self.layer_counts)) == 1)

    @property
    def stage_layer_counts(self) -> tuple:
        if self.layer_counts is not None:
            return self.layer_counts
        return (self.num_layers // self.num_stages,) * self.num_stages

    @property
    def layers_per_stage(self) -> int:
        """Uniform per-stage count — only meaningful for even partitions."""
        if not self.is_even:
            raise ValueError(
                f"layers_per_stage is undefined for the uneven partition "
                f"{self.layer_counts}; use stage_layer_counts/max_layers_per_stage")
        return self.stage_layer_counts[0]

    @property
    def max_layers_per_stage(self) -> int:
        """Slot count of the padded stacked layout [num_stages, k_max, ...]."""
        return max(self.stage_layer_counts)

    # -- interleaved (virtual_stages > 1) chunk geometry --------------------

    @property
    def layers_per_chunk(self) -> int:
        """Layer count of one virtual chunk — the k of the interleaved
        stacked layout [num_stages, virtual_stages, k, ...]."""
        return self.num_layers // (self.num_stages * self.virtual_stages)

    def chunk_of_layer(self, layer_idx: int) -> tuple:
        """(stage, virtual_chunk) of a layer under the round-robin
        assignment ((stage, 0) for every layer of a flat manifest's stage)."""
        if not 0 <= layer_idx < self.num_layers:
            raise ValueError(f"layer {layer_idx} out of range [0, {self.num_layers})")
        if self.virtual_stages == 1:
            return self.stage_of_layer(layer_idx), 0
        c = layer_idx // self.layers_per_chunk
        return c % self.num_stages, c // self.num_stages

    def layers_of_chunk(self, stage: int, virtual_chunk: int) -> range:
        """Layer range of one (stage, virtual_chunk) cell."""
        if not 0 <= stage < self.num_stages:
            raise ValueError(f"stage {stage} out of range [0, {self.num_stages})")
        if not 0 <= virtual_chunk < self.virtual_stages:
            raise ValueError(f"virtual chunk {virtual_chunk} out of range "
                             f"[0, {self.virtual_stages})")
        if self.virtual_stages == 1:
            return self.layers_of_stage(stage)
        k = self.layers_per_chunk
        off = (virtual_chunk * self.num_stages + stage) * k
        return range(off, off + k)

    # embed lives on the first stage, final norm + lm head on the last
    # (reference layer-list order, models/llama_ds_mp_wrap.py:213-219)
    embed_stage: int = 0

    @property
    def head_stage(self) -> int:
        return self.num_stages - 1

    def stage_offsets(self) -> tuple:
        """Start layer index of each stage (cumulative counts)."""
        out, acc = [], 0
        for c in self.stage_layer_counts:
            out.append(acc)
            acc += c
        return tuple(out)

    def stage_of_layer(self, layer_idx: int) -> int:
        if not 0 <= layer_idx < self.num_layers:
            raise ValueError(f"layer {layer_idx} out of range [0, {self.num_layers})")
        if self.virtual_stages > 1:
            return (layer_idx // self.layers_per_chunk) % self.num_stages
        for s, (off, c) in enumerate(zip(self.stage_offsets(),
                                         self.stage_layer_counts)):
            if off <= layer_idx < off + c:
                return s
        raise AssertionError("unreachable")

    def layers_of_stage(self, stage: int):
        """Layer indices owned by one stage: a contiguous range for flat
        manifests, the sorted union of the stage's virtual chunks (a list —
        NON-contiguous) under interleaving."""
        if not 0 <= stage < self.num_stages:
            raise ValueError(f"stage {stage} out of range [0, {self.num_stages})")
        if self.virtual_stages > 1:
            return [layer for vc in range(self.virtual_stages)
                    for layer in self.layers_of_chunk(stage, vc)]
        off = self.stage_offsets()[stage]
        return range(off, off + self.stage_layer_counts[stage])

    @staticmethod
    def for_config(cfg: LlamaConfig, num_stages: int,
                   virtual_stages: int = 1) -> "StageManifest":
        return StageManifest(num_layers=cfg.num_hidden_layers,
                             num_stages=num_stages,
                             virtual_stages=virtual_stages)

    @staticmethod
    def balanced(cfg: LlamaConfig, num_stages: int,
                 embed_weight: float | None = None,
                 head_weight: float | None = None) -> "StageManifest":
        """Cost-balanced partition: minimize the max per-stage cost, where a
        stage's cost is its decoder-layer count plus the embed / lm-head
        weight (in layer units) it hosts.

        Default weights come from the model's matmul flops: one decoder layer
        moves ~2*(2*d^2 + 2*d*kv + 3*d*f) flops/token forward; the lm-head
        (and its loss softmax) ~2*d*V; the embedding gather is ~free forward
        but its backward is a scatter into [V, d], counted like half a head.
        This is the stage-balance lever SURVEY.md §7.3 item 2 calls the MFU
        determinant (DeepSpeed's partition_method="parameters" analogue).
        """
        d, f, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
        kv_dim = cfg.kv_heads * cfg.head_dim
        layer_cost = 2 * d * d + 2 * d * kv_dim + 3 * d * f
        if head_weight is None:
            head_weight = (d * v) / layer_cost
        if embed_weight is None:
            embed_weight = 0.5 * (d * v) / layer_cost
        n, s = cfg.num_hidden_layers, num_stages
        if s > n:
            raise ValueError(f"num_stages={s} exceeds num_layers={n}: every "
                             f"stage needs at least one decoder layer")
        if s == 1:
            return StageManifest(num_layers=n, num_stages=s)
        extras = [0.0] * s
        extras[0] += embed_weight
        extras[-1] += head_weight

        counts = [1] * s
        for _ in range(n - s):  # greedily grow the currently-cheapest stage
            j = min(range(s), key=lambda i: (counts[i] + extras[i], i))
            counts[j] += 1
        manifest = StageManifest(num_layers=n, num_stages=s,
                                 layer_counts=tuple(counts))
        return (StageManifest(num_layers=n, num_stages=s)
                if manifest.is_even and n % s == 0 else manifest)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @staticmethod
    def from_json(s: str) -> "StageManifest":
        return StageManifest(**json.loads(s))

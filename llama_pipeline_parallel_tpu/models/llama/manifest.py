"""Layer -> pipeline-stage manifest.

The reference encodes the stage partition twice, implicitly: once as layer-list
order (models/llama_ds_mp_wrap.py:213-219) and once as checkpoint filename
arithmetic (convert2ckpt.py:24-36, `layer_{i+1:02d}-model_00-...`), and the
two must stay in lockstep by convention. Here the mapping is one explicit,
serializable object that both the pipeline runtime and the checkpoint engine
consume — which is also what makes PP-topology-changing restores possible
(SURVEY.md §7.3 item 5).
"""

from __future__ import annotations

import dataclasses
import json

from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig


@dataclasses.dataclass(frozen=True)
class StageManifest:
    num_layers: int
    num_stages: int

    def __post_init__(self) -> None:
        if self.num_stages < 1:
            raise ValueError(f"num_stages must be >= 1, got {self.num_stages}")
        if self.num_layers % self.num_stages:
            raise ValueError(
                f"num_layers={self.num_layers} not divisible by "
                f"num_stages={self.num_stages}; uneven stage partitions are not "
                f"supported yet (cost-balanced partitioning is a planned knob)"
            )

    @property
    def layers_per_stage(self) -> int:
        return self.num_layers // self.num_stages

    # embed lives on the first stage, final norm + lm head on the last
    # (reference layer-list order, models/llama_ds_mp_wrap.py:213-219)
    embed_stage: int = 0

    @property
    def head_stage(self) -> int:
        return self.num_stages - 1

    def stage_of_layer(self, layer_idx: int) -> int:
        if not 0 <= layer_idx < self.num_layers:
            raise ValueError(f"layer {layer_idx} out of range [0, {self.num_layers})")
        return layer_idx // self.layers_per_stage

    def layers_of_stage(self, stage: int) -> range:
        if not 0 <= stage < self.num_stages:
            raise ValueError(f"stage {stage} out of range [0, {self.num_stages})")
        k = self.layers_per_stage
        return range(stage * k, (stage + 1) * k)

    @staticmethod
    def for_config(cfg: LlamaConfig, num_stages: int) -> "StageManifest":
        return StageManifest(num_layers=cfg.num_hidden_layers, num_stages=num_stages)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @staticmethod
    def from_json(s: str) -> "StageManifest":
        return StageManifest(**json.loads(s))

"""Pure-functional LLaMA with stacked layer parameters.

Design notes (vs the reference):
- The reference cuts an HF `LlamaForCausalLM` into a flat list of DeepSpeed
  `LayerSpec`s (reference models/llama_ds_mp_wrap.py:209-224: EmbeddingPipe,
  k x ParallelTransformerLayerPipe, LayerNormPipe, LMLayerPipe). Here the same
  partition exists as *data layout*: all decoder layers share one pytree whose
  leaves carry a leading `num_hidden_layers` axis. A single-device forward
  `lax.scan`s over that axis; the pipeline runtime reshapes it to
  `[num_stages, layers_per_stage, ...]` and shards the stage axis over the
  `pp` mesh axis (see parallel/pipeline.py). No per-layer Python objects, no
  filename arithmetic.
- Embedding / final norm / lm-head are separate top-level entries, placed on
  the first/last stage by the pipeline runtime (reference stage predicates
  trainer_base_ds_mp.py:309).
- No weight tying between embed and lm_head (reference README.md:44-46).
- Params are kept in `param_dtype` (fp32 master) and cast to `dtype` (bf16)
  at forward entry — the bf16 analogue of DeepSpeed's fp16 master-weight
  machinery (reference conf yaml fp16 block), with no loss scaling needed.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.ops.attention import attention
from llama_pipeline_parallel_tpu.ops.rmsnorm import rms_norm
from llama_pipeline_parallel_tpu.ops.rope import apply_rope, rope_cos_sin

Params = dict
AttnFn = Callable[..., jnp.ndarray]

IGNORE_INDEX = -100  # label value excluded from the loss (reference data/flan.py:187)


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------

def init_params(rng: jax.Array, cfg: LlamaConfig) -> Params:
    """Random init (normal 0.02, HF default) with stacked layer leaves."""
    n, d, f, v = (cfg.num_hidden_layers, cfg.hidden_size,
                  cfg.intermediate_size, cfg.vocab_size)
    kv_dim = cfg.kv_heads * cfg.head_dim
    keys = jax.random.split(rng, 9)
    pd = cfg.param_dtype

    def nrm(key, shape):
        return (jax.random.normal(key, shape, jnp.float32) * 0.02).astype(pd)

    return {
        "embed": {"embedding": nrm(keys[0], (v, d))},
        "layers": {
            "attn": {
                "wq": nrm(keys[1], (n, d, d)),
                "wk": nrm(keys[2], (n, d, kv_dim)),
                "wv": nrm(keys[3], (n, d, kv_dim)),
                "wo": nrm(keys[4], (n, d, d)),
            },
            "mlp": {
                "gate": nrm(keys[5], (n, d, f)),
                "up": nrm(keys[6], (n, d, f)),
                "down": nrm(keys[7], (n, f, d)),
            },
            "input_norm": jnp.ones((n, d), pd),
            "post_norm": jnp.ones((n, d), pd),
        },
        "norm": jnp.ones((d,), pd),
        "lm_head": nrm(keys[8], (d, v)),
    }


def cast_params(params: Params, dtype) -> Params:
    return jax.tree.map(lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
                        params)


# ---------------------------------------------------------------------------
# Forward pieces (each maps onto one reference pipe-layer class)
# ---------------------------------------------------------------------------

def embed(params: Params, input_ids: jnp.ndarray, cfg: LlamaConfig) -> jnp.ndarray:
    """Token embedding (reference EmbeddingPipe, models/llama_ds_mp_wrap.py:128-132)."""
    return params["embed"]["embedding"].astype(cfg.dtype)[input_ids]


def decoder_layer(
    layer: Params,
    x: jnp.ndarray,
    padding_mask: jnp.ndarray | None,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    cfg: LlamaConfig,
    attn_fn: AttnFn = attention,
    tp_axis: str | None = None,
    pallas_prologue: bool = False,
) -> jnp.ndarray:
    """One transformer block (reference ParallelTransformerLayerPipe,
    models/llama_ds_mp_wrap.py:135-181, which wraps HF LlamaDecoderLayer).

    `tp_axis`: when set (inside shard_map with column/row-sharded weights),
    qkv/gate/up are column-parallel and wo/down row-parallel, with the
    Megatron f/g operator pair from parallel/tp.py. Head counts are derived
    from the LOCAL weight shards, so the same code runs tp=1 and tp=N.

    `pallas_prologue` (config `kernels.prologue: pallas`) runs
    rms_norm -> RoPE -> q/k/v as one fused Pallas kernel
    (ops/pallas_prologue.py) — same numerics within the pinned tolerance,
    the normed hidden never round-trips HBM; its custom VJP carries the
    tp_copy psum internally, so both branches compose with tp identically.
    """
    b, s, d = x.shape
    hd = cfg.head_dim
    dt = cfg.dtype

    if tp_axis is not None:
        from llama_pipeline_parallel_tpu.parallel.tp import tp_copy, tp_reduce
    wq = layer["attn"]["wq"].astype(dt)
    wk = layer["attn"]["wk"].astype(dt)
    wv = layer["attn"]["wv"].astype(dt)
    h_local = wq.shape[-1] // hd
    kv_local = wk.shape[-1] // hd

    residual = x
    if pallas_prologue:
        from llama_pipeline_parallel_tpu.ops.pallas_prologue import fused_prologue

        q, k, v = fused_prologue(
            x, layer["input_norm"], wq, wk, wv, cos, sin,
            eps=cfg.rms_norm_eps, head_dim=hd, tp_axis=tp_axis)
    else:
        hidden = rms_norm(x, layer["input_norm"], cfg.rms_norm_eps)
        if tp_axis is not None:
            hidden = tp_copy(hidden, tp_axis)
        q = (hidden @ wq).reshape(b, s, h_local, hd)
        k = (hidden @ wk).reshape(b, s, kv_local, hd)
        v = (hidden @ wv).reshape(b, s, kv_local, hd)
        q, k = apply_rope(q, k, cos, sin)
    attn_out = attn_fn(q, k, v, padding_mask, causal=True)
    attn_out = attn_out.reshape(b, s, -1) @ layer["attn"]["wo"].astype(dt)
    if tp_axis is not None:
        attn_out = tp_reduce(attn_out, tp_axis)
    x = residual + attn_out

    return mlp_block(layer, x, cfg, tp_axis=tp_axis)


def mlp_block(layer: Params, x: jnp.ndarray, cfg: LlamaConfig,
              tp_axis: str | None = None) -> jnp.ndarray:
    """Post-norm SwiGLU half of a decoder block (shared with the KV-cache
    decode path, models/llama/decode.py — one implementation, no numerics
    drift between training and generation)."""
    dt = cfg.dtype
    residual = x
    hidden = rms_norm(x, layer["post_norm"], cfg.rms_norm_eps)
    if tp_axis is not None:
        from llama_pipeline_parallel_tpu.parallel.tp import tp_copy, tp_reduce

        hidden = tp_copy(hidden, tp_axis)
    gate = jax.nn.silu(hidden @ layer["mlp"]["gate"].astype(dt))
    up = hidden @ layer["mlp"]["up"].astype(dt)
    mlp_out = (gate * up) @ layer["mlp"]["down"].astype(dt)
    if tp_axis is not None:
        mlp_out = tp_reduce(mlp_out, tp_axis)
    return residual + mlp_out


def run_layers(
    layers: Params,
    x: jnp.ndarray,
    padding_mask: jnp.ndarray | None,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    cfg: LlamaConfig,
    attn_fn: AttnFn = attention,
    remat: bool = False,
    tp_axis: str | None = None,
    remat_policy: str = "nothing_saveable",
    slot_valid: jnp.ndarray | None = None,
    pallas_prologue: bool = False,
) -> jnp.ndarray:
    """Apply a stack of layers (leading axis on every leaf) via lax.scan.

    `remat=True` recomputes each layer in backward — the analogue of
    `deepspeed.checkpointing.checkpoint` per layer (reference
    models/llama_ds_mp_wrap.py:57,166; flag conf yaml `activation_checkpointing`).
    `remat_policy` trades recompute FLOPs for memory: `nothing_saveable`
    (max memory savings), `dots_saveable` / `dots_with_no_batch_dims_saveable`
    (keep matmul outputs, recompute only elementwise — cheaper backward).
    `slot_valid` ([num_layers] bool): cond-skip invalid slots — the uneven
    pipeline partition's zero-weight padding (parallel/pipeline.py). The
    caller must ONLY pass this when the layer body is collective-free
    (tp_axis None, no sp attention): a collective inside a branch that other
    devices skip aborts the runtime.
    """

    def compute(layer, h):
        return decoder_layer(layer, h, padding_mask, cos, sin, cfg, attn_fn,
                             tp_axis=tp_axis, pallas_prologue=pallas_prologue)

    if slot_valid is None:
        def body(h, layer):
            return compute(layer, h), None

        xs = layers
    else:
        if tp_axis is not None:
            raise ValueError("slot_valid cond-skip cannot be combined with "
                             "tp collectives inside the layer")

        def body(h, xs_):
            layer, valid = xs_
            return jax.lax.cond(valid, compute, lambda layer_, h_: h_, layer, h), None

        xs = (layers, slot_valid)

    if remat:
        body = jax.checkpoint(body, policy=resolve_remat_policy(remat_policy))
    x, _ = jax.lax.scan(body, x, xs)
    return x


# Directly-usable jax.checkpoint policies, by config name. Factory attributes
# (save_only_these_names, ...) need construction arguments and are excluded —
# name-based selection would fail cryptically at first trace.
REMAT_POLICIES = (
    "nothing_saveable",
    "everything_saveable",
    "dots_saveable",
    "checkpoint_dots",  # alias of dots_saveable
    "dots_with_no_batch_dims_saveable",
    "checkpoint_dots_with_no_batch_dims",  # alias
)


def resolve_remat_policy(name: str):
    if name not in REMAT_POLICIES:
        raise ValueError(f"unknown remat_policy {name!r}; choose one of {REMAT_POLICIES}")
    return getattr(jax.checkpoint_policies, name)


def final_norm(params: Params, x: jnp.ndarray, cfg: LlamaConfig) -> jnp.ndarray:
    """Final RMSNorm (reference LayerNormPipe, models/llama_ds_mp_wrap.py:184-188)."""
    return rms_norm(x, params["norm"], cfg.rms_norm_eps)


def lm_head(params: Params, x: jnp.ndarray, cfg: LlamaConfig) -> jnp.ndarray:
    """Logits projection (reference LMLayerPipe, models/llama_ds_mp_wrap.py:191-195).
    Returns fp32 logits for a stable softmax-CE."""
    return (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)


def forward(
    params: Params,
    input_ids: jnp.ndarray,
    attention_mask: jnp.ndarray | None = None,
    position_ids: jnp.ndarray | None = None,
    *,
    cfg: LlamaConfig,
    attn_fn: AttnFn = attention,
    remat: bool = False,
    pallas_prologue: bool = False,
) -> jnp.ndarray:
    """Single-device full forward: the PP=1 degenerate schedule.

    Batch protocol matches the reference collator output
    `(input_ids, attention_mask, position_ids)` (reference data/flan.py:304-307)
    with `attention_mask` as per-token [b, s] SEGMENT IDS (0 = pad; packed
    batches number each example 1..k and attention masks cross-segment
    pairs; plain batches use all-1s) — NOT a materialized [b, 1, L, L]
    tensor (SURVEY.md §3.5 fix). See ops/attention.py.
    """
    b, s = input_ids.shape
    if position_ids is None:
        position_ids = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    cos, sin = rope_cos_sin(position_ids, cfg.head_dim, cfg.rope_theta, dtype=cfg.dtype)
    x = embed(params, input_ids, cfg)
    x = run_layers(params["layers"], x, attention_mask, cos, sin, cfg, attn_fn,
                   remat, pallas_prologue=pallas_prologue)
    x = final_norm(params, x, cfg)
    return lm_head(params, x, cfg)


# ---------------------------------------------------------------------------
# Loss
# ---------------------------------------------------------------------------

def token_loss_sum_and_count_preshifted(
    logits: jnp.ndarray, target_labels: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """CE where `target_labels[:, i]` is already the next-token target for
    `logits[:, i]` (positions with no target carry IGNORE_INDEX). This is the
    form sequence-parallel shards need: the causal shift crosses sp-shard
    boundaries, so the caller aligns targets (parallel/pipeline.py
    `_sp_shift_labels`) and the loss itself stays shard-local."""
    valid = target_labels != IGNORE_INDEX
    safe_labels = jnp.where(valid, target_labels, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    token_ll = jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    loss_sum = jnp.where(valid, -token_ll, 0.0).sum()
    return loss_sum, valid.sum()


def token_loss_sum_and_count(logits: jnp.ndarray, labels: jnp.ndarray
                             ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Shifted causal-LM cross-entropy: (sum of token losses, valid-token count).

    The single source of truth for shift/IGNORE_INDEX masking semantics —
    both the single-device loss below and the pipeline's last-stage loss
    (parallel/pipeline.py) build on it, so they cannot drift apart.
    """
    return token_loss_sum_and_count_preshifted(logits[:, :-1, :], labels[:, 1:])


def loss_fn(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Token-mean shifted cross-entropy with IGNORE_INDEX masking.

    Mirrors the reference `loss_fn` (models/llama_ds_mp_wrap.py:105-116) minus
    its index-column bug (labels there carried a smuggled extra column,
    SURVEY.md §3.5): labels here are exactly [b, s].
    """
    loss_sum, count = token_loss_sum_and_count(logits, labels)
    return loss_sum / jnp.maximum(count, 1)

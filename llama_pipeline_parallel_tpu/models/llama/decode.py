"""Autoregressive KV-cache decoding.

Fills the reference's dead prediction surface with a real one: its config
gestures at an evaluator/prediction step (reference conf yaml:107-115
`prediction_cfg`, `general_util.evaluator.DiscriminatorForwardFn` — the class
is absent and no predict path exists, SURVEY.md §2.4), while this module
implements batched generation the TPU way:

- ONE jitted program per phase: a prefill pass over the (left-padded) prompt
  and a `lax.scan` decode loop with a static-shape KV cache — no per-token
  retracing, no dynamic shapes, nothing for XLA to re-tile.
- The KV cache is a stacked `[n_layers, b, max_len, kv_heads, head_dim]`
  array pair written with `dynamic_update_slice` — the same stacked-leading-
  axis layout the training stack uses for layer params, so the layer loop
  stays a `lax.scan` over layers.
- Left-padded prompts: per-row rope positions come from the attention mask's
  cumulative sum, causality during decode reduces to the KV validity mask
  (a single [b, max_len] 0/1 array), and every row writes the same cache slot
  each step — no per-row dynamic slicing.

Models too big for one chip shard WITHOUT code changes: Megatron-shard the
params over a tp mesh (column-parallel qkv/gate/up, row-parallel wo/down,
vocab-parallel lm_head) and call the same jitted `generate` — GSPMD inserts
the collectives, and tokens match the unsharded run exactly
(tests/test_decode.py::test_generate_with_tp_sharded_params). Pipelined
decode across pp stages is a training-economy trade the reference never had
either and is out of scope.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.ops.attention import attention
from llama_pipeline_parallel_tpu.ops.rmsnorm import rms_norm
from llama_pipeline_parallel_tpu.ops.rope import apply_rope, rope_cos_sin

Params = dict


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    max_new_tokens: int = 64
    temperature: float = 0.0     # 0 -> greedy
    top_k: int = 0               # 0 -> full distribution
    top_p: float = 1.0           # nucleus mass; 1.0 -> no nucleus filter
    eos_token_id: int | None = None
    pad_token_id: int = 0        # emitted after a row hits eos

    def __post_init__(self) -> None:
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1 (the decode loop "
                             "always emits the prefill-sampled token)")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")


def init_kv_cache(cfg: LlamaConfig, batch: int, max_len: int) -> dict:
    """Zeroed static-shape cache. k/v: [n_layers, b, max_len, kv_h, hd]."""
    shape = (cfg.num_hidden_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def _layer_forward_cached(layer: Params, x: jnp.ndarray, cache_k: jnp.ndarray,
                          cache_v: jnp.ndarray, write_pos, kv_mask: jnp.ndarray,
                          cos: jnp.ndarray, sin: jnp.ndarray, cfg: LlamaConfig,
                          causal: bool) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One decoder layer reading/writing its KV cache slice.

    x: [b, s, d] (s = prompt length at prefill, 1 at decode);
    cache_k/v: [b, max_len, kv_h, hd]; write_pos: scalar slot index for x's
    first position (uniform across rows — left padding makes that possible);
    kv_mask: [b, max_len] validity of every cache slot INCLUDING x's own
    positions.

    `causal=True` is the PREFILL contract: the block is the entire visible
    history (write_pos must be 0), so attention runs over the freshly
    projected k/v at prompt-length cost — never over the max_len cache whose
    future slots are all masked anyway. `causal=False` is the decode step:
    x is one token attending over the whole cache, visibility is purely
    kv_mask.
    """
    b, s, d = x.shape
    hd = cfg.head_dim
    dt = cfg.dtype

    hidden = rms_norm(x, layer["input_norm"], cfg.rms_norm_eps)
    q = (hidden @ layer["attn"]["wq"].astype(dt)).reshape(b, s, -1, hd)
    k = (hidden @ layer["attn"]["wk"].astype(dt)).reshape(b, s, -1, hd)
    v = (hidden @ layer["attn"]["wv"].astype(dt)).reshape(b, s, -1, hd)
    q, k = apply_rope(q, k, cos, sin)

    cache_k = jax.lax.dynamic_update_slice(cache_k, k, (0, write_pos, 0, 0))
    cache_v = jax.lax.dynamic_update_slice(cache_v, v, (0, write_pos, 0, 0))

    if causal:  # prefill: nothing precedes the block; attend within it
        attn_out = attention(q, k, v, kv_mask[:, :s], causal=True)
    else:       # decode: one token over the full cache, mask-gated
        attn_out = attention(q, cache_k, cache_v, kv_mask, causal=False)
    attn_out = attn_out.reshape(b, s, -1) @ layer["attn"]["wo"].astype(dt)
    x = llama.mlp_block(layer, x + attn_out, cfg)
    return x, cache_k, cache_v


def forward_with_cache(params: Params, input_ids: jnp.ndarray, cache: dict,
                       positions: jnp.ndarray, write_pos, kv_mask: jnp.ndarray,
                       cfg: LlamaConfig, causal: bool = True,
                       last_only: bool = False) -> tuple[jnp.ndarray, dict]:
    """Embed -> cached layers (lax.scan) -> final norm -> logits.

    positions: [b, s] rope positions of input_ids (per-row under left
    padding). Returns fp32 logits [b, s, V] and the updated cache.
    `last_only` projects logits for the FINAL position only (prefill needs
    just the next-token distribution — [b, P, V] fp32 logits for a long
    prompt would be the dominant prefill allocation, for one used row).
    """
    x = llama.embed(params, input_ids, cfg)
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta, dtype=cfg.dtype)

    def body(h, xs):
        layer, ck, cv = xs
        h, ck, cv = _layer_forward_cached(layer, h, ck, cv, write_pos, kv_mask,
                                          cos, sin, cfg, causal)
        return h, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(body, x, (params["layers"], cache["k"], cache["v"]))
    if last_only:
        x = x[:, -1:, :]
    x = llama.final_norm(params, x, cfg)
    return llama.lm_head(params, x, cfg), {"k": new_k, "v": new_v}


def _top_p_mask(logits: jnp.ndarray, top_p) -> jnp.ndarray:
    """Nucleus filter: keep the smallest descending-sorted prefix whose
    cumulative probability reaches `top_p`; everything else to -inf.

    Keep rule is `cumulative mass BEFORE the token < top_p`, so the argmax
    always survives (a top_p below the top token's own probability degrades
    to greedy, never to an empty support). Shape-agnostic over leading dims
    — the serving path runs it per row with a traced scalar `top_p`, and
    both paths share this exact arithmetic so their tokens match bit-for-bit.
    """
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    before = jnp.cumsum(probs, axis=-1) - probs
    keep = before < top_p
    cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1,
                     keepdims=True)
    return jnp.where(logits < cutoff, -jnp.inf, logits)


def _sample(logits: jnp.ndarray, gen: GenerationConfig, rng: jax.Array) -> jnp.ndarray:
    """[b, V] fp32 logits -> [b] int32 next tokens."""
    if gen.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / gen.temperature
    if gen.top_k > 0:
        kth = jax.lax.top_k(logits, gen.top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if gen.top_p < 1.0:
        logits = _top_p_mask(logits, gen.top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def _sample_row(logits: jnp.ndarray, temperature, top_k, top_p,
                key: jax.Array) -> jnp.ndarray:
    """[V] logits -> scalar token, with PER-REQUEST knobs as traced values.

    The serving batch mixes requests with different GenerationConfigs, so
    the static branches of `_sample` become data: greedy is selected by
    `where(temperature > 0)`, the top-k threshold is the k-th largest VALUE
    (the same element `lax.top_k` finds, read off a descending sort), and
    the nucleus filter is the shared `_top_p_mask`. Every arithmetic path
    mirrors `_sample` exactly, which is what makes a slot-served request
    reproduce an independent `generate()` call token-for-token.
    """
    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe_t = jnp.where(temperature > 0.0, temperature, 1.0)
    l = logits / safe_t
    sorted_desc = jnp.sort(l, axis=-1)[..., ::-1]
    kth = sorted_desc[jnp.clip(top_k, 1, vocab) - 1]
    l = jnp.where((top_k > 0) & (l < kth), -jnp.inf, l)
    l = jnp.where(top_p < 1.0, _top_p_mask(l, top_p), l)
    sampled = jax.random.categorical(key, l, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)


def sample_rowwise(logits: jnp.ndarray, temperature: jnp.ndarray,
                   top_k: jnp.ndarray, top_p: jnp.ndarray,
                   keys: jnp.ndarray) -> jnp.ndarray:
    """[b, V] logits + [b] per-row knobs + [b, 2] keys -> [b] tokens."""
    return jax.vmap(_sample_row)(logits, temperature, top_k, top_p, keys)


@partial(jax.jit, static_argnames=("cfg", "gen"))
def generate(params: Params, input_ids: jnp.ndarray, attention_mask: jnp.ndarray,
             cfg: LlamaConfig, gen: GenerationConfig,
             rng: jax.Array | None = None) -> dict:
    """Batched generation from LEFT-padded prompts.

    input_ids/attention_mask: [b, P] with pads on the left (mask 0 = pad).
    Returns {"tokens": [b, max_new_tokens] int32 (pad_token_id after eos),
    "done": [b] bool (row hit eos within the budget)}.

    Params are the CANONICAL (unstacked) layout — `pl.unstack_stages` a
    training tree first, or load one with `tools/convert_hf.py` output.
    """
    b, prompt_len = input_ids.shape
    max_len = prompt_len + gen.max_new_tokens
    if rng is None:
        rng = jax.random.PRNGKey(0)
    mask = attention_mask.astype(jnp.int32)

    # Per-row rope positions: pads get clipped to 0, real tokens count from 0.
    positions = jnp.clip(jnp.cumsum(mask, axis=1) - 1, 0, None).astype(jnp.int32)

    cache = init_kv_cache(cfg, b, max_len)
    kv_mask = jnp.pad(mask, ((0, 0), (0, gen.max_new_tokens)))
    logits, cache = forward_with_cache(
        params, input_ids, cache, positions, 0, kv_mask, cfg, causal=True,
        last_only=True)

    next_pos = positions[:, -1] + 1            # [b] rope position of token P
    rng, first_key = jax.random.split(rng)     # use-once key discipline
    first = _sample(logits[:, -1, :], gen, first_key)

    def step(carry, t):
        cache, token, pos, kv_mask, done, rng = carry
        rng, sub = jax.random.split(rng)
        write_pos = prompt_len + t
        kv_mask = kv_mask.at[:, write_pos].set(1)
        logits, cache = forward_with_cache(
            params, token[:, None], cache, pos[:, None], write_pos, kv_mask,
            cfg, causal=False)
        nxt = _sample(logits[:, -1, :], gen, sub)
        out = jnp.where(done, gen.pad_token_id, token)
        if gen.eos_token_id is not None:
            done = done | (token == gen.eos_token_id)
        nxt = jnp.where(done, token, nxt)      # freeze finished rows
        return (cache, nxt, pos + 1, kv_mask, done, rng), out

    # Scan T-1 steps: the T-th sampled token needs no forward pass of its
    # own (nothing consumes its logits), so the final emission happens
    # outside the loop — at max_new_tokens=1 the decode scan is empty.
    carry = (cache, first, next_pos, kv_mask, jnp.zeros((b,), bool), rng)
    (_, token, _, _, done, _), tokens = jax.lax.scan(
        step, carry, jnp.arange(gen.max_new_tokens - 1))
    last = jnp.where(done, gen.pad_token_id, token)
    if gen.eos_token_id is not None:
        done = done | (token == gen.eos_token_id)
    tokens = jnp.concatenate([tokens, last[None]], axis=0)
    return {"tokens": tokens.T, "done": done}


# -- continuous-batching entry points (serve/) -------------------------------
#
# `generate()` owns a whole batch cradle-to-grave: one shared prompt bucket,
# one scalar write position, cache re-initialized per call. Serving needs the
# same kernels with the batch axis reinterpreted as SLOTS that requests join
# and leave independently: the cache is allocated ONCE at [max_slots,
# max_len], `prefill_prompt` produces a row to splice in, and `decode_step`
# advances every slot one token with PER-ROW write positions, rope positions,
# rng chains, and sampling knobs. The arithmetic per row is identical to
# generate()'s — serve/engine.py leans on that for its token-parity contract.


@partial(jax.jit, static_argnames=("cfg", "max_len"))
def prefill_prompt(params: Params, input_ids: jnp.ndarray,
                   attention_mask: jnp.ndarray, cfg: LlamaConfig,
                   max_len: int) -> dict:
    """Prefill LEFT-padded prompts into fresh max_len-sized cache rows.

    input_ids/attention_mask: [b, P] (P = the prompt bucket; per-request
    length variation lives in the left padding, so one compile per bucket).
    Returns {"logits": [b, V] fp32 last-position logits, "cache": k/v
    [L, b, max_len, kv_h, hd] with prompt kv at [0, P), "kv_mask":
    [b, max_len], "next_pos": [b] rope position of the first generated
    token}. The next write position is P — uniform, the caller knows it
    statically.
    """
    b, prompt_len = input_ids.shape
    if prompt_len > max_len:
        raise ValueError(f"prompt bucket {prompt_len} exceeds cache max_len "
                         f"{max_len}")
    mask = attention_mask.astype(jnp.int32)
    positions = jnp.clip(jnp.cumsum(mask, axis=1) - 1, 0, None).astype(jnp.int32)
    cache = init_kv_cache(cfg, b, max_len)
    kv_mask = jnp.pad(mask, ((0, 0), (0, max_len - prompt_len)))
    logits, cache = forward_with_cache(
        params, input_ids, cache, positions, 0, kv_mask, cfg, causal=True,
        last_only=True)
    return {"logits": logits[:, -1], "cache": cache, "kv_mask": kv_mask,
            "next_pos": positions[:, -1] + 1}


def _layer_decode_rowwise(layer: Params, x: jnp.ndarray, cache_k: jnp.ndarray,
                          cache_v: jnp.ndarray, write_pos: jnp.ndarray,
                          kv_mask: jnp.ndarray, cos: jnp.ndarray,
                          sin: jnp.ndarray, cfg: LlamaConfig
                          ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """`_layer_forward_cached`'s decode branch with write_pos: [b] — each
    slot writes its own cache position (requests at different depths share
    one decode tick), via a vmapped per-row dynamic_update_slice."""
    b, s, d = x.shape
    hd = cfg.head_dim
    dt = cfg.dtype

    hidden = rms_norm(x, layer["input_norm"], cfg.rms_norm_eps)
    q = (hidden @ layer["attn"]["wq"].astype(dt)).reshape(b, s, -1, hd)
    k = (hidden @ layer["attn"]["wk"].astype(dt)).reshape(b, s, -1, hd)
    v = (hidden @ layer["attn"]["wv"].astype(dt)).reshape(b, s, -1, hd)
    q, k = apply_rope(q, k, cos, sin)

    row_update = lambda c, n, w: jax.lax.dynamic_update_slice(c, n, (w, 0, 0))
    cache_k = jax.vmap(row_update)(cache_k, k, write_pos)
    cache_v = jax.vmap(row_update)(cache_v, v, write_pos)

    attn_out = attention(q, cache_k, cache_v, kv_mask, causal=False)
    attn_out = attn_out.reshape(b, s, -1) @ layer["attn"]["wo"].astype(dt)
    x = llama.mlp_block(layer, x + attn_out, cfg)
    return x, cache_k, cache_v


@partial(jax.jit, static_argnames=("cfg",),
         donate_argnames=("cache", "kv_mask"))
def decode_step(params: Params, token: jnp.ndarray, cache: dict,
                pos: jnp.ndarray, write_pos: jnp.ndarray,
                kv_mask: jnp.ndarray, keys: jnp.ndarray,
                temperature: jnp.ndarray, top_k: jnp.ndarray,
                top_p: jnp.ndarray, cfg: LlamaConfig) -> dict:
    """One continuous-batching decode tick over every slot row.

    token/pos/write_pos: [b] int32; cache: k/v [L, b, max_len, kv_h, hd];
    kv_mask: [b, max_len]; keys: [b, 2] per-request rng chains;
    temperature/top_k/top_p: [b] per-request sampling knobs. Free slots ride
    along (static shape, one compile): their kv_mask rows are garbage and
    their sampled tokens are discarded by the host scheduler — admission
    rewrites the whole row.

    Each row mirrors one `generate()` scan step exactly: mark write_pos
    valid BEFORE the forward (the token attends to itself), advance the rng
    chain with the same `split(rng) -> (chain, sub)` discipline, sample
    with the same arithmetic. Returns {"token": [b] next tokens, "cache",
    "kv_mask", "keys"}; rope/write positions advance by one — the caller
    tracks them host-side.
    """
    b = token.shape[0]
    kv_mask = kv_mask.at[jnp.arange(b), write_pos].set(1)

    x = llama.embed(params, token[:, None], cfg)
    cos, sin = rope_cos_sin(pos[:, None], cfg.head_dim, cfg.rope_theta,
                            dtype=cfg.dtype)

    def body(h, xs):
        layer, ck, cv = xs
        h, ck, cv = _layer_decode_rowwise(layer, h, ck, cv, write_pos,
                                          kv_mask, cos, sin, cfg)
        return h, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(body, x,
                                     (params["layers"], cache["k"], cache["v"]))
    x = llama.final_norm(params, x, cfg)
    logits = llama.lm_head(params, x, cfg)[:, -1, :]

    split = jax.vmap(jax.random.split)(keys)        # [b, 2, 2]
    nxt = sample_rowwise(logits, temperature, top_k, top_p, split[:, 1])
    return {"token": nxt, "cache": {"k": new_k, "v": new_v},
            "kv_mask": kv_mask, "keys": split[:, 0]}


@partial(jax.jit, donate_argnames=("cache", "kv_mask"))
def write_slot(cache: dict, kv_mask: jnp.ndarray, slot: jnp.ndarray,
               row_cache: dict, row_kv_mask: jnp.ndarray
               ) -> tuple[dict, jnp.ndarray]:
    """Splice one prefilled request (`prefill_prompt` output, b == 1) into
    slot row `slot` of the long-lived serving cache. `slot` is traced, so
    admission reuses one compiled program for every slot index."""
    cache = {
        "k": jax.lax.dynamic_update_slice(
            cache["k"], row_cache["k"], (0, slot, 0, 0, 0)),
        "v": jax.lax.dynamic_update_slice(
            cache["v"], row_cache["v"], (0, slot, 0, 0, 0)),
    }
    kv_mask = jax.lax.dynamic_update_slice(kv_mask, row_kv_mask, (slot, 0))
    return cache, kv_mask

"""Autoregressive KV-cache decoding.

Fills the reference's dead prediction surface with a real one: its config
gestures at an evaluator/prediction step (reference conf yaml:107-115
`prediction_cfg`, `general_util.evaluator.DiscriminatorForwardFn` — the class
is absent and no predict path exists, SURVEY.md §2.4), while this module
implements batched generation the TPU way:

- ONE jitted program per phase: a prefill pass over the (left-padded) prompt
  and a `lax.scan` decode loop with a static-shape KV cache — no per-token
  retracing, no dynamic shapes, nothing for XLA to re-tile.
- The KV cache is a stacked `[n_layers, b, max_len, kv_heads, head_dim]`
  array pair written with `dynamic_update_slice` — the same stacked-leading-
  axis layout the training stack uses for layer params, so the layer loop
  stays a `lax.scan` over layers.
- Left-padded prompts: per-row rope positions come from the attention mask's
  cumulative sum, causality during decode reduces to the KV validity mask
  (a single [b, max_len] 0/1 array), and every row writes the same cache slot
  each step — no per-row dynamic slicing.

Models too big for one chip shard WITHOUT code changes: Megatron-shard the
params over a tp mesh (column-parallel qkv/gate/up, row-parallel wo/down,
vocab-parallel lm_head) and call the same jitted `generate` — GSPMD inserts
the collectives, and tokens match the unsharded run exactly
(tests/test_decode.py::test_generate_with_tp_sharded_params). Pipelined
decode across pp stages is a training-economy trade the reference never had
either and is out of scope.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.ops.attention import attention
from llama_pipeline_parallel_tpu.ops.rmsnorm import rms_norm
from llama_pipeline_parallel_tpu.ops.rope import apply_rope, rope_cos_sin

Params = dict


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    max_new_tokens: int = 64
    temperature: float = 0.0     # 0 -> greedy
    top_k: int = 0               # 0 -> full distribution
    eos_token_id: int | None = None
    pad_token_id: int = 0        # emitted after a row hits eos

    def __post_init__(self) -> None:
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1 (the decode loop "
                             "always emits the prefill-sampled token)")


def init_kv_cache(cfg: LlamaConfig, batch: int, max_len: int) -> dict:
    """Zeroed static-shape cache. k/v: [n_layers, b, max_len, kv_h, hd]."""
    shape = (cfg.num_hidden_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def _layer_forward_cached(layer: Params, x: jnp.ndarray, cache_k: jnp.ndarray,
                          cache_v: jnp.ndarray, write_pos, kv_mask: jnp.ndarray,
                          cos: jnp.ndarray, sin: jnp.ndarray, cfg: LlamaConfig,
                          causal: bool) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One decoder layer reading/writing its KV cache slice.

    x: [b, s, d] (s = prompt length at prefill, 1 at decode);
    cache_k/v: [b, max_len, kv_h, hd]; write_pos: scalar slot index for x's
    first position (uniform across rows — left padding makes that possible);
    kv_mask: [b, max_len] validity of every cache slot INCLUDING x's own
    positions.

    `causal=True` is the PREFILL contract: the block is the entire visible
    history (write_pos must be 0), so attention runs over the freshly
    projected k/v at prompt-length cost — never over the max_len cache whose
    future slots are all masked anyway. `causal=False` is the decode step:
    x is one token attending over the whole cache, visibility is purely
    kv_mask.
    """
    b, s, d = x.shape
    hd = cfg.head_dim
    dt = cfg.dtype

    hidden = rms_norm(x, layer["input_norm"], cfg.rms_norm_eps)
    q = (hidden @ layer["attn"]["wq"].astype(dt)).reshape(b, s, -1, hd)
    k = (hidden @ layer["attn"]["wk"].astype(dt)).reshape(b, s, -1, hd)
    v = (hidden @ layer["attn"]["wv"].astype(dt)).reshape(b, s, -1, hd)
    q, k = apply_rope(q, k, cos, sin)

    cache_k = jax.lax.dynamic_update_slice(cache_k, k, (0, write_pos, 0, 0))
    cache_v = jax.lax.dynamic_update_slice(cache_v, v, (0, write_pos, 0, 0))

    if causal:  # prefill: nothing precedes the block; attend within it
        attn_out = attention(q, k, v, kv_mask[:, :s], causal=True)
    else:       # decode: one token over the full cache, mask-gated
        attn_out = attention(q, cache_k, cache_v, kv_mask, causal=False)
    attn_out = attn_out.reshape(b, s, -1) @ layer["attn"]["wo"].astype(dt)
    x = llama.mlp_block(layer, x + attn_out, cfg)
    return x, cache_k, cache_v


def forward_with_cache(params: Params, input_ids: jnp.ndarray, cache: dict,
                       positions: jnp.ndarray, write_pos, kv_mask: jnp.ndarray,
                       cfg: LlamaConfig, causal: bool = True,
                       last_only: bool = False) -> tuple[jnp.ndarray, dict]:
    """Embed -> cached layers (lax.scan) -> final norm -> logits.

    positions: [b, s] rope positions of input_ids (per-row under left
    padding). Returns fp32 logits [b, s, V] and the updated cache.
    `last_only` projects logits for the FINAL position only (prefill needs
    just the next-token distribution — [b, P, V] fp32 logits for a long
    prompt would be the dominant prefill allocation, for one used row).
    """
    x = llama.embed(params, input_ids, cfg)
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta, dtype=cfg.dtype)

    def body(h, xs):
        layer, ck, cv = xs
        h, ck, cv = _layer_forward_cached(layer, h, ck, cv, write_pos, kv_mask,
                                          cos, sin, cfg, causal)
        return h, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(body, x, (params["layers"], cache["k"], cache["v"]))
    if last_only:
        x = x[:, -1:, :]
    x = llama.final_norm(params, x, cfg)
    return llama.lm_head(params, x, cfg), {"k": new_k, "v": new_v}


def _sample(logits: jnp.ndarray, gen: GenerationConfig, rng: jax.Array) -> jnp.ndarray:
    """[b, V] fp32 logits -> [b] int32 next tokens."""
    if gen.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / gen.temperature
    if gen.top_k > 0:
        kth = jax.lax.top_k(logits, gen.top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("cfg", "gen"))
def generate(params: Params, input_ids: jnp.ndarray, attention_mask: jnp.ndarray,
             cfg: LlamaConfig, gen: GenerationConfig,
             rng: jax.Array | None = None) -> dict:
    """Batched generation from LEFT-padded prompts.

    input_ids/attention_mask: [b, P] with pads on the left (mask 0 = pad).
    Returns {"tokens": [b, max_new_tokens] int32 (pad_token_id after eos),
    "done": [b] bool (row hit eos within the budget)}.

    Params are the CANONICAL (unstacked) layout — `pl.unstack_stages` a
    training tree first, or load one with `tools/convert_hf.py` output.
    """
    b, prompt_len = input_ids.shape
    max_len = prompt_len + gen.max_new_tokens
    if rng is None:
        rng = jax.random.PRNGKey(0)
    mask = attention_mask.astype(jnp.int32)

    # Per-row rope positions: pads get clipped to 0, real tokens count from 0.
    positions = jnp.clip(jnp.cumsum(mask, axis=1) - 1, 0, None).astype(jnp.int32)

    cache = init_kv_cache(cfg, b, max_len)
    kv_mask = jnp.pad(mask, ((0, 0), (0, gen.max_new_tokens)))
    logits, cache = forward_with_cache(
        params, input_ids, cache, positions, 0, kv_mask, cfg, causal=True,
        last_only=True)

    next_pos = positions[:, -1] + 1            # [b] rope position of token P
    rng, first_key = jax.random.split(rng)     # use-once key discipline
    first = _sample(logits[:, -1, :], gen, first_key)

    def step(carry, t):
        cache, token, pos, kv_mask, done, rng = carry
        rng, sub = jax.random.split(rng)
        write_pos = prompt_len + t
        kv_mask = kv_mask.at[:, write_pos].set(1)
        logits, cache = forward_with_cache(
            params, token[:, None], cache, pos[:, None], write_pos, kv_mask,
            cfg, causal=False)
        nxt = _sample(logits[:, -1, :], gen, sub)
        out = jnp.where(done, gen.pad_token_id, token)
        if gen.eos_token_id is not None:
            done = done | (token == gen.eos_token_id)
        nxt = jnp.where(done, token, nxt)      # freeze finished rows
        return (cache, nxt, pos + 1, kv_mask, done, rng), out

    # Scan T-1 steps: the T-th sampled token needs no forward pass of its
    # own (nothing consumes its logits), so the final emission happens
    # outside the loop — at max_new_tokens=1 the decode scan is empty.
    carry = (cache, first, next_pos, kv_mask, jnp.zeros((b,), bool), rng)
    (_, token, _, _, done, _), tokens = jax.lax.scan(
        step, carry, jnp.arange(gen.max_new_tokens - 1))
    last = jnp.where(done, gen.pad_token_id, token)
    if gen.eos_token_id is not None:
        done = done | (token == gen.eos_token_id)
    tokens = jnp.concatenate([tokens, last[None]], axis=0)
    return {"tokens": tokens.T, "done": done}

"""Autoregressive KV-cache decoding.

Fills the reference's dead prediction surface with a real one: its config
gestures at an evaluator/prediction step (reference conf yaml:107-115
`prediction_cfg`, `general_util.evaluator.DiscriminatorForwardFn` — the class
is absent and no predict path exists, SURVEY.md §2.4), while this module
implements batched generation the TPU way:

- ONE jitted program per phase: a prefill pass over the (left-padded) prompt
  and a `lax.scan` decode loop with a static-shape KV cache — no per-token
  retracing, no dynamic shapes, nothing for XLA to re-tile.
- The KV cache is a stacked `[n_layers, b, max_len, kv_heads, head_dim]`
  array pair written with `dynamic_update_slice` — the same stacked-leading-
  axis layout the training stack uses for layer params, so the layer loop
  stays a `lax.scan` over layers.
- Left-padded prompts: per-row rope positions come from the attention mask's
  cumulative sum, causality during decode reduces to the KV validity mask
  (a single [b, max_len] 0/1 array), and every row writes the same cache slot
  each step — no per-row dynamic slicing.

Models too big for one chip shard WITHOUT code changes: Megatron-shard the
params over a tp mesh (column-parallel qkv/gate/up, row-parallel wo/down,
vocab-parallel lm_head) and call the same jitted `generate` — GSPMD inserts
the collectives, and tokens match the unsharded run exactly
(tests/test_decode.py::test_generate_with_tp_sharded_params). Pipelined
decode across pp stages is a training-economy trade the reference never had
either and is out of scope.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.ops.attention import attention
from llama_pipeline_parallel_tpu.ops.rmsnorm import rms_norm
from llama_pipeline_parallel_tpu.ops.rope import apply_rope, rope_cos_sin

Params = dict


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    max_new_tokens: int = 64
    temperature: float = 0.0     # 0 -> greedy
    top_k: int = 0               # 0 -> full distribution
    top_p: float = 1.0           # nucleus mass; 1.0 -> no nucleus filter
    eos_token_id: int | None = None
    pad_token_id: int = 0        # emitted after a row hits eos

    def __post_init__(self) -> None:
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1 (the decode loop "
                             "always emits the prefill-sampled token)")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")


def init_kv_cache(cfg: LlamaConfig, batch: int, max_len: int) -> dict:
    """Zeroed static-shape cache. k/v: [n_layers, b, max_len, kv_h, hd]."""
    shape = (cfg.num_hidden_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def _layer_forward_cached(layer: Params, x: jnp.ndarray, cache_k: jnp.ndarray,
                          cache_v: jnp.ndarray, write_pos, kv_mask: jnp.ndarray,
                          cos: jnp.ndarray, sin: jnp.ndarray, cfg: LlamaConfig,
                          causal: bool) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One decoder layer reading/writing its KV cache slice.

    x: [b, s, d] (s = prompt length at prefill, 1 at decode);
    cache_k/v: [b, max_len, kv_h, hd]; write_pos: scalar slot index for x's
    first position (uniform across rows — left padding makes that possible);
    kv_mask: [b, max_len] validity of every cache slot INCLUDING x's own
    positions.

    `causal=True` is the PREFILL contract: the block is the entire visible
    history (write_pos must be 0), so attention runs over the freshly
    projected k/v at prompt-length cost — never over the max_len cache whose
    future slots are all masked anyway. `causal=False` is the decode step:
    x is one token attending over the whole cache, visibility is purely
    kv_mask.
    """
    b, s, d = x.shape
    hd = cfg.head_dim
    dt = cfg.dtype

    hidden = rms_norm(x, layer["input_norm"], cfg.rms_norm_eps)
    q = (hidden @ layer["attn"]["wq"].astype(dt)).reshape(b, s, -1, hd)
    k = (hidden @ layer["attn"]["wk"].astype(dt)).reshape(b, s, -1, hd)
    v = (hidden @ layer["attn"]["wv"].astype(dt)).reshape(b, s, -1, hd)
    q, k = apply_rope(q, k, cos, sin)

    cache_k = jax.lax.dynamic_update_slice(cache_k, k, (0, write_pos, 0, 0))
    cache_v = jax.lax.dynamic_update_slice(cache_v, v, (0, write_pos, 0, 0))

    if causal:  # prefill: nothing precedes the block; attend within it
        attn_out = attention(q, k, v, kv_mask[:, :s], causal=True)
    else:       # decode: one token over the full cache, mask-gated
        attn_out = attention(q, cache_k, cache_v, kv_mask, causal=False)
    attn_out = attn_out.reshape(b, s, -1) @ layer["attn"]["wo"].astype(dt)
    x = llama.mlp_block(layer, x + attn_out, cfg)
    return x, cache_k, cache_v


def forward_with_cache(params: Params, input_ids: jnp.ndarray, cache: dict,
                       positions: jnp.ndarray, write_pos, kv_mask: jnp.ndarray,
                       cfg: LlamaConfig, causal: bool = True,
                       last_only: bool = False) -> tuple[jnp.ndarray, dict]:
    """Embed -> cached layers (lax.scan) -> final norm -> logits.

    positions: [b, s] rope positions of input_ids (per-row under left
    padding). Returns fp32 logits [b, s, V] and the updated cache.
    `last_only` projects logits for the FINAL position only (prefill needs
    just the next-token distribution — [b, P, V] fp32 logits for a long
    prompt would be the dominant prefill allocation, for one used row).
    """
    x = llama.embed(params, input_ids, cfg)
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta, dtype=cfg.dtype)

    def body(h, xs):
        layer, ck, cv = xs
        h, ck, cv = _layer_forward_cached(layer, h, ck, cv, write_pos, kv_mask,
                                          cos, sin, cfg, causal)
        return h, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(body, x, (params["layers"], cache["k"], cache["v"]))
    if last_only:
        x = x[:, -1:, :]
    x = llama.final_norm(params, x, cfg)
    return llama.lm_head(params, x, cfg), {"k": new_k, "v": new_v}


def _top_p_mask(logits: jnp.ndarray, top_p) -> jnp.ndarray:
    """Nucleus filter: keep the smallest descending-sorted prefix whose
    cumulative probability reaches `top_p`; everything else to -inf.

    Keep rule is `cumulative mass BEFORE the token < top_p`, so the argmax
    always survives (a top_p below the top token's own probability degrades
    to greedy, never to an empty support). Shape-agnostic over leading dims
    — the serving path runs it per row with a traced scalar `top_p`, and
    both paths share this exact arithmetic so their tokens match bit-for-bit.
    """
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    before = jnp.cumsum(probs, axis=-1) - probs
    keep = before < top_p
    cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1,
                     keepdims=True)
    return jnp.where(logits < cutoff, -jnp.inf, logits)


def _sample(logits: jnp.ndarray, gen: GenerationConfig, rng: jax.Array) -> jnp.ndarray:
    """[b, V] fp32 logits -> [b] int32 next tokens."""
    if gen.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / gen.temperature
    if gen.top_k > 0:
        kth = jax.lax.top_k(logits, gen.top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if gen.top_p < 1.0:
        logits = _top_p_mask(logits, gen.top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def _sample_row(logits: jnp.ndarray, temperature, top_k, top_p,
                key: jax.Array) -> jnp.ndarray:
    """[V] logits -> scalar token, with PER-REQUEST knobs as traced values.

    The serving batch mixes requests with different GenerationConfigs, so
    the static branches of `_sample` become data: greedy is selected by
    `where(temperature > 0)`, the top-k threshold is the k-th largest VALUE
    (the same element `lax.top_k` finds, read off a descending sort), and
    the nucleus filter is the shared `_top_p_mask`. Every arithmetic path
    mirrors `_sample` exactly, which is what makes a slot-served request
    reproduce an independent `generate()` call token-for-token.
    """
    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe_t = jnp.where(temperature > 0.0, temperature, 1.0)
    l = logits / safe_t
    sorted_desc = jnp.sort(l, axis=-1)[..., ::-1]
    kth = sorted_desc[jnp.clip(top_k, 1, vocab) - 1]
    l = jnp.where((top_k > 0) & (l < kth), -jnp.inf, l)
    l = jnp.where(top_p < 1.0, _top_p_mask(l, top_p), l)
    sampled = jax.random.categorical(key, l, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)


def sample_rowwise(logits: jnp.ndarray, temperature: jnp.ndarray,
                   top_k: jnp.ndarray, top_p: jnp.ndarray,
                   keys: jnp.ndarray) -> jnp.ndarray:
    """[b, V] logits + [b] per-row knobs + [b, 2] keys -> [b] tokens."""
    return jax.vmap(_sample_row)(logits, temperature, top_k, top_p, keys)


@partial(jax.jit, static_argnames=("cfg", "gen"))
def generate(params: Params, input_ids: jnp.ndarray, attention_mask: jnp.ndarray,
             cfg: LlamaConfig, gen: GenerationConfig,
             rng: jax.Array | None = None) -> dict:
    """Batched generation from LEFT-padded prompts.

    input_ids/attention_mask: [b, P] with pads on the left (mask 0 = pad).
    Returns {"tokens": [b, max_new_tokens] int32 (pad_token_id after eos),
    "done": [b] bool (row hit eos within the budget)}.

    Params are the CANONICAL (unstacked) layout — `pl.unstack_stages` a
    training tree first, or load one with `tools/convert_hf.py` output.
    """
    b, prompt_len = input_ids.shape
    max_len = prompt_len + gen.max_new_tokens
    if rng is None:
        rng = jax.random.PRNGKey(0)
    mask = attention_mask.astype(jnp.int32)

    # Per-row rope positions: pads get clipped to 0, real tokens count from 0.
    positions = jnp.clip(jnp.cumsum(mask, axis=1) - 1, 0, None).astype(jnp.int32)

    cache = init_kv_cache(cfg, b, max_len)
    kv_mask = jnp.pad(mask, ((0, 0), (0, gen.max_new_tokens)))
    logits, cache = forward_with_cache(
        params, input_ids, cache, positions, 0, kv_mask, cfg, causal=True,
        last_only=True)

    next_pos = positions[:, -1] + 1            # [b] rope position of token P
    rng, first_key = jax.random.split(rng)     # use-once key discipline
    first = _sample(logits[:, -1, :], gen, first_key)

    def step(carry, t):
        cache, token, pos, kv_mask, done, rng = carry
        rng, sub = jax.random.split(rng)
        write_pos = prompt_len + t
        kv_mask = kv_mask.at[:, write_pos].set(1)
        logits, cache = forward_with_cache(
            params, token[:, None], cache, pos[:, None], write_pos, kv_mask,
            cfg, causal=False)
        nxt = _sample(logits[:, -1, :], gen, sub)
        out = jnp.where(done, gen.pad_token_id, token)
        if gen.eos_token_id is not None:
            done = done | (token == gen.eos_token_id)
        nxt = jnp.where(done, token, nxt)      # freeze finished rows
        return (cache, nxt, pos + 1, kv_mask, done, rng), out

    # Scan T-1 steps: the T-th sampled token needs no forward pass of its
    # own (nothing consumes its logits), so the final emission happens
    # outside the loop — at max_new_tokens=1 the decode scan is empty.
    carry = (cache, first, next_pos, kv_mask, jnp.zeros((b,), bool), rng)
    (_, token, _, _, done, _), tokens = jax.lax.scan(
        step, carry, jnp.arange(gen.max_new_tokens - 1))
    last = jnp.where(done, gen.pad_token_id, token)
    if gen.eos_token_id is not None:
        done = done | (token == gen.eos_token_id)
    tokens = jnp.concatenate([tokens, last[None]], axis=0)
    return {"tokens": tokens.T, "done": done}


# -- continuous-batching entry points (serve/) -------------------------------
#
# `generate()` owns a whole batch cradle-to-grave: one shared prompt bucket,
# one scalar write position, cache re-initialized per call. Serving needs the
# same kernels with the batch axis reinterpreted as SLOTS that requests join
# and leave independently: the cache is allocated ONCE at [max_slots,
# max_len], `prefill_prompt` produces a row to splice in, and `decode_step`
# advances every slot one token with PER-ROW write positions, rope positions,
# rng chains, and sampling knobs. The arithmetic per row is identical to
# generate()'s — serve/engine.py leans on that for its token-parity contract.


@partial(jax.jit, static_argnames=("cfg", "max_len"))
def prefill_prompt(params: Params, input_ids: jnp.ndarray,
                   attention_mask: jnp.ndarray, cfg: LlamaConfig,
                   max_len: int) -> dict:
    """Prefill LEFT-padded prompts into fresh max_len-sized cache rows.

    input_ids/attention_mask: [b, P] (P = the prompt bucket; per-request
    length variation lives in the left padding, so one compile per bucket).
    Returns {"logits": [b, V] fp32 last-position logits, "cache": k/v
    [L, b, max_len, kv_h, hd] with prompt kv at [0, P), "kv_mask":
    [b, max_len], "next_pos": [b] rope position of the first generated
    token}. The next write position is P — uniform, the caller knows it
    statically.
    """
    b, prompt_len = input_ids.shape
    if prompt_len > max_len:
        raise ValueError(f"prompt bucket {prompt_len} exceeds cache max_len "
                         f"{max_len}")
    mask = attention_mask.astype(jnp.int32)
    positions = jnp.clip(jnp.cumsum(mask, axis=1) - 1, 0, None).astype(jnp.int32)
    cache = init_kv_cache(cfg, b, max_len)
    kv_mask = jnp.pad(mask, ((0, 0), (0, max_len - prompt_len)))
    logits, cache = forward_with_cache(
        params, input_ids, cache, positions, 0, kv_mask, cfg, causal=True,
        last_only=True)
    return {"logits": logits[:, -1], "cache": cache, "kv_mask": kv_mask,
            "next_pos": positions[:, -1] + 1}


def _layer_decode_rowwise(layer: Params, x: jnp.ndarray, cache_k: jnp.ndarray,
                          cache_v: jnp.ndarray, write_pos: jnp.ndarray,
                          kv_mask: jnp.ndarray, cos: jnp.ndarray,
                          sin: jnp.ndarray, cfg: LlamaConfig
                          ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """`_layer_forward_cached`'s decode branch with write_pos: [b] — each
    slot writes its own cache position (requests at different depths share
    one decode tick), via a vmapped per-row dynamic_update_slice."""
    b, s, d = x.shape
    hd = cfg.head_dim
    dt = cfg.dtype

    hidden = rms_norm(x, layer["input_norm"], cfg.rms_norm_eps)
    q = (hidden @ layer["attn"]["wq"].astype(dt)).reshape(b, s, -1, hd)
    k = (hidden @ layer["attn"]["wk"].astype(dt)).reshape(b, s, -1, hd)
    v = (hidden @ layer["attn"]["wv"].astype(dt)).reshape(b, s, -1, hd)
    q, k = apply_rope(q, k, cos, sin)

    row_update = lambda c, n, w: jax.lax.dynamic_update_slice(c, n, (w, 0, 0))
    cache_k = jax.vmap(row_update)(cache_k, k, write_pos)
    cache_v = jax.vmap(row_update)(cache_v, v, write_pos)

    attn_out = attention(q, cache_k, cache_v, kv_mask, causal=False)
    attn_out = attn_out.reshape(b, s, -1) @ layer["attn"]["wo"].astype(dt)
    x = llama.mlp_block(layer, x + attn_out, cfg)
    return x, cache_k, cache_v


@partial(jax.jit, static_argnames=("cfg",),
         donate_argnames=("cache", "kv_mask"))
def decode_step(params: Params, token: jnp.ndarray, cache: dict,
                pos: jnp.ndarray, write_pos: jnp.ndarray,
                kv_mask: jnp.ndarray, keys: jnp.ndarray,
                temperature: jnp.ndarray, top_k: jnp.ndarray,
                top_p: jnp.ndarray, cfg: LlamaConfig) -> dict:
    """One continuous-batching decode tick over every slot row.

    token/pos/write_pos: [b] int32; cache: k/v [L, b, max_len, kv_h, hd];
    kv_mask: [b, max_len]; keys: [b, 2] per-request rng chains;
    temperature/top_k/top_p: [b] per-request sampling knobs. Free slots ride
    along (static shape, one compile): their kv_mask rows are garbage and
    their sampled tokens are discarded by the host scheduler — admission
    rewrites the whole row.

    Each row mirrors one `generate()` scan step exactly: mark write_pos
    valid BEFORE the forward (the token attends to itself), advance the rng
    chain with the same `split(rng) -> (chain, sub)` discipline, sample
    with the same arithmetic. Returns {"token": [b] next tokens, "cache",
    "kv_mask", "keys"}; rope/write positions advance by one — the caller
    tracks them host-side.
    """
    b = token.shape[0]
    kv_mask = kv_mask.at[jnp.arange(b), write_pos].set(1)

    x = llama.embed(params, token[:, None], cfg)
    cos, sin = rope_cos_sin(pos[:, None], cfg.head_dim, cfg.rope_theta,
                            dtype=cfg.dtype)

    def body(h, xs):
        layer, ck, cv = xs
        h, ck, cv = _layer_decode_rowwise(layer, h, ck, cv, write_pos,
                                          kv_mask, cos, sin, cfg)
        return h, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(body, x,
                                     (params["layers"], cache["k"], cache["v"]))
    x = llama.final_norm(params, x, cfg)
    logits = llama.lm_head(params, x, cfg)[:, -1, :]

    split = jax.vmap(jax.random.split)(keys)        # [b, 2, 2]
    nxt = sample_rowwise(logits, temperature, top_k, top_p, split[:, 1])
    return {"token": nxt, "cache": {"k": new_k, "v": new_v},
            "kv_mask": kv_mask, "keys": split[:, 0]}


@partial(jax.jit, donate_argnames=("cache", "kv_mask"))
def write_slot(cache: dict, kv_mask: jnp.ndarray, slot: jnp.ndarray,
               row_cache: dict, row_kv_mask: jnp.ndarray
               ) -> tuple[dict, jnp.ndarray]:
    """Splice one prefilled request (`prefill_prompt` output, b == 1) into
    slot row `slot` of the long-lived serving cache. `slot` is traced, so
    admission reuses one compiled program for every slot index."""
    cache = {
        "k": jax.lax.dynamic_update_slice(
            cache["k"], row_cache["k"], (0, slot, 0, 0, 0)),
        "v": jax.lax.dynamic_update_slice(
            cache["v"], row_cache["v"], (0, slot, 0, 0, 0)),
    }
    kv_mask = jax.lax.dynamic_update_slice(kv_mask, row_kv_mask, (slot, 0))
    return cache, kv_mask


# -- paged continuous-batching entry points (serve/pages.py) ------------------
#
# The slot cache above reserves `[max_slots, max_len]` rows up front: one
# long request's worst case is charged to EVERY slot. The paged variants
# below keep the same static-shape discipline (one compile per program, no
# per-batch retracing) but back the logical rows with fixed-size PAGES from
# a shared pool plus a slot->page table, so resident HBM tracks tokens
# actually written. The logical view a slot sees is still `[max_len]` =
# `pages_per_slot * page_size` — the gather below reconstitutes it per
# layer — which is what makes the fp paged decode token-bit-exact against
# the dense path: post-mask score arrays are identical (garbage pages only
# ever contribute through masked positions, whose scores are the same
# NEG_INF constant and whose softmax weights are exactly 0.0).
#
# int8 pages (`quant="int8"`) store one fp32 scale per (layer, page,
# kv_head): prefill writes whole pages and set the scale from the block
# absmax; decode writes claim a fresh page at offset 0 (pages fill in
# strict logical order) and set its scale from the first token, later
# offsets saturate against it. Dequantization happens on read, in fp32,
# before the cast to the compute dtype — serve/engine.py tolerance-gates
# this path instead of claiming bit parity.


def init_page_pool(cfg: LlamaConfig, num_pages: int, page_size: int,
                   quant: str = "fp") -> dict:
    """Zeroed page pool. k/v: [n_layers, num_pages + 1, page_size, kv_h, hd]
    — ONE extra garbage page at index `num_pages`: released/inactive slots
    point every logical page at it, so their rides through the static-shape
    decode step scatter there instead of into live data. int8 pools carry
    k_scale/v_scale: [n_layers, num_pages + 1, kv_h] fp32 per-page scales."""
    shape = (cfg.num_hidden_layers, num_pages + 1, page_size, cfg.kv_heads,
             cfg.head_dim)
    dt = jnp.int8 if quant == "int8" else cfg.dtype
    pool = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
    if quant == "int8":
        sshape = (cfg.num_hidden_layers, num_pages + 1, cfg.kv_heads)
        pool["k_scale"] = jnp.zeros(sshape, jnp.float32)
        pool["v_scale"] = jnp.zeros(sshape, jnp.float32)
    return pool


# absmax floor: an all-zero block quantizes against this instead of 0/0
_SCALE_FLOOR = 1e-8


def quant_page_block(x: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """fp -> int8 against a per-(page, kv_head) scale (broadcast over the
    page and head_dim axes). Saturating: values beyond the scale clip."""
    q = jnp.round(x.astype(jnp.float32) * (127.0 / scale))
    return jnp.clip(q, -127, 127).astype(jnp.int8)


def dequant_page_block(q: jnp.ndarray, scale: jnp.ndarray,
                       dtype) -> jnp.ndarray:
    """int8 -> fp32 dequant against the per-page scale, then the compute-
    dtype cast (the 'fp32 dequant-on-read' half of the contract)."""
    return (q.astype(jnp.float32) * (scale / 127.0)).astype(dtype)


def _block_amax(x: jnp.ndarray, axes) -> jnp.ndarray:
    return jnp.maximum(jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axes),
                       _SCALE_FLOOR)


def _gather_pages(pool_k, pool_v, sc_k, sc_v, page_table: jnp.ndarray,
                  dtype):
    """Reconstitute logical kv rows from the pool: [*, Pmax] page indices ->
    [*, Pmax * page_size, kv_h, hd] in the compute dtype."""
    gk = pool_k[page_table]
    gv = pool_v[page_table]
    if sc_k is not None:
        gk = dequant_page_block(gk, sc_k[page_table][..., None, :, None], dtype)
        gv = dequant_page_block(gv, sc_v[page_table][..., None, :, None], dtype)
    *lead, pmax, page, kvh, hd = gk.shape
    return (gk.reshape(*lead, pmax * page, kvh, hd),
            gv.reshape(*lead, pmax * page, kvh, hd))


@partial(jax.jit, donate_argnames=("pool", "kv_mask"))
def write_pages(pool: dict, kv_mask: jnp.ndarray, slot: jnp.ndarray,
                page_rows: jnp.ndarray, row_cache: dict,
                row_kv_mask: jnp.ndarray) -> tuple[dict, jnp.ndarray]:
    """Splice one prefilled request into its physical pages: the paged
    counterpart of `write_slot`. `row_cache` is a `prefill_prompt` result
    taken at max_len == the prompt bucket (k/v: [L, 1, bucket, kv_h, hd],
    bucket a multiple of page_size), `page_rows` the [bucket / page_size]
    physical pages the slot owns for it. The logical kv_mask row `slot` is
    rewritten WHOLE (zeros past the bucket), so whatever a previous
    occupant left in the row is dead after admission."""
    L, _, bucket, kvh, hd = row_cache["k"].shape
    n_pages = page_rows.shape[0]
    page = bucket // n_pages
    quant = pool["k"].dtype == jnp.int8
    out = dict(pool)
    for name in ("k", "v"):
        blocks = row_cache[name].reshape(L, n_pages, page, kvh, hd)
        if quant:
            scale = _block_amax(blocks, axes=(2, 4))          # [L, n, kvh]
            out[f"{name}_scale"] = out[f"{name}_scale"].at[:, page_rows].set(
                scale)
            blocks = quant_page_block(blocks, scale[:, :, None, :, None])
        out[name] = out[name].at[:, page_rows].set(blocks)
    lmax = kv_mask.shape[1]
    row = jnp.pad(row_kv_mask.astype(kv_mask.dtype),
                  ((0, 0), (0, lmax - bucket)))
    kv_mask = jax.lax.dynamic_update_slice(kv_mask, row, (slot, 0))
    return out, kv_mask


@jax.jit
def reset_kv_mask_row(kv_mask: jnp.ndarray, slot: jnp.ndarray) -> jnp.ndarray:
    """Zero logical row `slot` — chunked prefill writes the row
    incrementally, so the previous occupant's mask must die up front (the
    single-shot `write_pages` path overwrites the whole row instead)."""
    zeros = jnp.zeros((1, kv_mask.shape[1]), kv_mask.dtype)
    return jax.lax.dynamic_update_slice(kv_mask, zeros, (slot, 0))


@partial(jax.jit, donate_argnames=("pool",))
def copy_page(pool: dict, src: jnp.ndarray, dst: jnp.ndarray) -> dict:
    """Clone physical page `src` into `dst` across every layer — the
    copy-on-write fork of prefix caching (serve/pages.py): a request whose
    prompt diverges MID-page from a cached chain copies the shared page,
    then overwrites only the divergent suffix in its private copy. int8
    pools bring the per-page scales along, so the copied prefix dequantizes
    identically to the source. `src`/`dst` are traced int32 scalars: one
    compiled program serves every fork."""
    out = dict(pool)
    for name in list(pool):
        blk = jax.lax.dynamic_index_in_dim(pool[name], src, axis=1,
                                           keepdims=True)
        out[name] = jax.lax.dynamic_update_slice_in_dim(out[name], blk, dst,
                                                        axis=1)
    return out


@jax.jit
def set_kv_mask_row(kv_mask: jnp.ndarray, slot: jnp.ndarray,
                    row: jnp.ndarray) -> jnp.ndarray:
    """Rewrite logical row `slot` whole from a host-built [1, max_len] row
    — the warm-admission counterpart of `reset_kv_mask_row`: a prefix-cache
    hit marks its shared positions valid (and everything past them dead) in
    ONE compiled update before the span prefill fills in the tail."""
    return jax.lax.dynamic_update_slice(kv_mask, row.astype(kv_mask.dtype),
                                        (slot, 0))


def _paged_write_token(pool_k, sc_k, x1: jnp.ndarray, w_page: jnp.ndarray,
                       w_off: jnp.ndarray):
    """Scatter one token's kv rows ([b, kv_h, hd]) into their pages. int8:
    offset 0 claims the page and sets its scale from this token's absmax
    (pages fill in strict logical order, so offset 0 == a fresh page);
    later offsets saturate against the existing scale."""
    if sc_k is None:
        return pool_k.at[w_page, w_off].set(x1), None
    amax = _block_amax(x1, axes=-1)                            # [b, kvh]
    scale = jnp.where((w_off == 0)[:, None], amax,
                      jnp.maximum(sc_k[w_page], _SCALE_FLOOR))
    sc_k = sc_k.at[w_page].set(scale)
    pool_k = pool_k.at[w_page, w_off].set(
        quant_page_block(x1, scale[:, :, None]))
    return pool_k, sc_k


def _layer_decode_paged(layer: Params, x: jnp.ndarray, pool_k, pool_v,
                        sc_k, sc_v, page_table: jnp.ndarray,
                        w_page: jnp.ndarray, w_off: jnp.ndarray,
                        kv_mask: jnp.ndarray, cos: jnp.ndarray,
                        sin: jnp.ndarray, cfg: LlamaConfig):
    """`_layer_decode_rowwise` over the page pool: write this token's kv
    into (w_page, w_off), gather each slot's logical row from its pages,
    attend mask-gated — same arithmetic, paged residency."""
    b, s, d = x.shape
    hd = cfg.head_dim
    dt = cfg.dtype

    hidden = rms_norm(x, layer["input_norm"], cfg.rms_norm_eps)
    q = (hidden @ layer["attn"]["wq"].astype(dt)).reshape(b, s, -1, hd)
    k = (hidden @ layer["attn"]["wk"].astype(dt)).reshape(b, s, -1, hd)
    v = (hidden @ layer["attn"]["wv"].astype(dt)).reshape(b, s, -1, hd)
    q, k = apply_rope(q, k, cos, sin)

    pool_k, sc_k = _paged_write_token(pool_k, sc_k, k[:, 0], w_page, w_off)
    pool_v, sc_v = _paged_write_token(pool_v, sc_v, v[:, 0], w_page, w_off)
    gk, gv = _gather_pages(pool_k, pool_v, sc_k, sc_v, page_table, dt)

    attn_out = attention(q, gk, gv, kv_mask, causal=False)
    attn_out = attn_out.reshape(b, s, -1) @ layer["attn"]["wo"].astype(dt)
    x = llama.mlp_block(layer, x + attn_out, cfg)
    return x, pool_k, pool_v, sc_k, sc_v


@partial(jax.jit, static_argnames=("cfg",),
         donate_argnames=("pool", "kv_mask"))
def paged_decode_step(params: Params, token: jnp.ndarray, pool: dict,
                      page_table: jnp.ndarray, pos: jnp.ndarray,
                      write_pos: jnp.ndarray, kv_mask: jnp.ndarray,
                      active: jnp.ndarray, keys: jnp.ndarray,
                      temperature: jnp.ndarray, top_k: jnp.ndarray,
                      top_p: jnp.ndarray, cfg: LlamaConfig) -> dict:
    """`decode_step` over the page pool: one tick over every slot row, with
    kv residency resolved through `page_table` ([S, pages_per_slot] physical
    page per logical page). `active`: [S] 0/1 — rows actually decoding.
    Inactive rows still ride the static shape, but their kv writes are
    steered to the garbage page and their kv_mask rows left untouched:
    unlike the dense cache (where a non-occupant row is dead until
    admission rewrites it whole), a paged slot can be MID-CHUNKED-PREFILL
    during the tick, already owning live pages and live mask spans that a
    stray write_pos=0 write would corrupt. The gathered logical view is
    [S, pages_per_slot * page_size] == [S, max_len], so the fp path is
    token-bit-exact against the dense `decode_step` (pinned in
    tests/test_paged_serving.py); int8 pools dequantize on read and are
    tolerance-gated instead."""
    b = token.shape[0]
    page = pool["k"].shape[2]
    garbage = pool["k"].shape[1] - 1
    # .max(): active rows mark write_pos valid (same as dense), inactive
    # rows keep whatever their mask row already says
    kv_mask = kv_mask.at[jnp.arange(b), write_pos].max(
        active.astype(kv_mask.dtype))
    w_page = jnp.take_along_axis(page_table, (write_pos // page)[:, None],
                                 axis=1)[:, 0]
    w_page = jnp.where(active > 0, w_page, garbage)
    w_off = write_pos % page

    x = llama.embed(params, token[:, None], cfg)
    cos, sin = rope_cos_sin(pos[:, None], cfg.head_dim, cfg.rope_theta,
                            dtype=cfg.dtype)
    quant = pool["k"].dtype == jnp.int8
    xs = ((params["layers"], pool["k"], pool["v"], pool["k_scale"],
           pool["v_scale"]) if quant
          else (params["layers"], pool["k"], pool["v"]))

    def body(h, xs):
        if quant:
            layer, pk, pv, sk, sv = xs
        else:
            (layer, pk, pv), sk, sv = xs, None, None
        h, pk, pv, sk, sv = _layer_decode_paged(
            layer, h, pk, pv, sk, sv, page_table, w_page, w_off, kv_mask,
            cos, sin, cfg)
        return h, ((pk, pv, sk, sv) if quant else (pk, pv))

    x, new = jax.lax.scan(body, x, xs)
    x = llama.final_norm(params, x, cfg)
    logits = llama.lm_head(params, x, cfg)[:, -1, :]

    split = jax.vmap(jax.random.split)(keys)        # [b, 2, 2]
    nxt = sample_rowwise(logits, temperature, top_k, top_p, split[:, 1])
    new_pool = {"k": new[0], "v": new[1]}
    if quant:
        new_pool["k_scale"], new_pool["v_scale"] = new[2], new[3]
    return {"token": nxt, "pool": new_pool, "kv_mask": kv_mask,
            "keys": split[:, 0]}


@partial(jax.jit, static_argnames=("cfg",),
         donate_argnames=("pool", "kv_mask"))
def paged_prefill_chunk(params: Params, input_ids: jnp.ndarray,
                        attention_mask: jnp.ndarray, positions: jnp.ndarray,
                        pool: dict, page_table_row: jnp.ndarray,
                        slot: jnp.ndarray, kv_mask: jnp.ndarray,
                        write_start: jnp.ndarray, cfg: LlamaConfig) -> dict:
    """One bounded prefill chunk of slot `slot`: embed chunk tokens
    ([1, C], C a multiple of page_size, logical span [write_start,
    write_start + C)), write their kv into the slot's pages, and attend
    each chunk position over the slot's FULL gathered logical row (history
    pages + the chunk itself) with a causal offset — the incremental half
    of chunked batched prefill. The engine interleaves these under the
    per-tick token budget so in-flight decodes never stall behind a long
    prompt. Returns the LAST position's fp32 logits (only the final chunk's
    are consumed, to sample the request's first token)."""
    _, C = input_ids.shape
    page = pool["k"].shape[2]
    hd = cfg.head_dim
    dt = cfg.dtype
    quant = pool["k"].dtype == jnp.int8

    mask = attention_mask.astype(jnp.int32)
    kv_mask = jax.lax.dynamic_update_slice(kv_mask, mask, (slot, write_start))
    lmax = kv_mask.shape[1]
    row_mask = jax.lax.dynamic_slice(kv_mask, (slot, 0), (1, lmax))

    chunk_pages = page_table_row[write_start // page +
                                 jnp.arange(C // page)]  # [C/page] physical

    x = llama.embed(params, input_ids, cfg)
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta,
                            dtype=cfg.dtype)
    xs = ((params["layers"], pool["k"], pool["v"], pool["k_scale"],
           pool["v_scale"]) if quant
          else (params["layers"], pool["k"], pool["v"]))

    def body(h, xs):
        if quant:
            layer, pk, pv, sk, sv = xs
        else:
            (layer, pk, pv), sk, sv = xs, None, None
        b, s, d = h.shape
        hidden = rms_norm(h, layer["input_norm"], cfg.rms_norm_eps)
        q = (hidden @ layer["attn"]["wq"].astype(dt)).reshape(b, s, -1, hd)
        k = (hidden @ layer["attn"]["wk"].astype(dt)).reshape(b, s, -1, hd)
        v = (hidden @ layer["attn"]["wv"].astype(dt)).reshape(b, s, -1, hd)
        q, k = apply_rope(q, k, cos, sin)

        kb = k[0].reshape(C // page, page, -1, hd)
        vb = v[0].reshape(C // page, page, -1, hd)
        if quant:
            ks = _block_amax(kb, axes=(1, 3))                 # [C/page, kvh]
            vs = _block_amax(vb, axes=(1, 3))
            sk = sk.at[chunk_pages].set(ks)
            sv = sv.at[chunk_pages].set(vs)
            kb = quant_page_block(kb, ks[:, None, :, None])
            vb = quant_page_block(vb, vs[:, None, :, None])
        pk = pk.at[chunk_pages].set(kb)
        pv = pv.at[chunk_pages].set(vb)

        gk, gv = _gather_pages(pk, pv, sk, sv, page_table_row[None], dt)
        attn_out = attention(q, gk, gv, row_mask, causal=True,
                             q_offset=write_start)
        attn_out = attn_out.reshape(b, s, -1) @ layer["attn"]["wo"].astype(dt)
        h = llama.mlp_block(layer, h + attn_out, cfg)
        return h, ((pk, pv, sk, sv) if quant else (pk, pv))

    x, new = jax.lax.scan(body, x, xs)
    x = llama.final_norm(params, x[:, -1:, :], cfg)
    logits = llama.lm_head(params, x, cfg)
    new_pool = {"k": new[0], "v": new[1]}
    if quant:
        new_pool["k_scale"], new_pool["v_scale"] = new[2], new[3]
    return {"logits": logits[:, -1], "pool": new_pool, "kv_mask": kv_mask}


@partial(jax.jit, static_argnames=("cfg",),
         donate_argnames=("pool", "kv_mask"))
def paged_prefill_span(params: Params, input_ids: jnp.ndarray,
                       attention_mask: jnp.ndarray, positions: jnp.ndarray,
                       pool: dict, page_table_row: jnp.ndarray,
                       slot: jnp.ndarray, kv_mask: jnp.ndarray,
                       write_start: jnp.ndarray, cfg: LlamaConfig) -> dict:
    """`paged_prefill_chunk` without the page-alignment constraints: prefill
    logical span [write_start, write_start + C) of slot `slot` where
    NEITHER the start nor the length is a page multiple — the tail a
    prefix-cache hit recomputes from its divergence point (serve/pages.py).
    Writes are per-token scatters into (page, offset) pairs instead of
    whole-page blocks, so the span can begin mid-page inside a freshly
    forked copy-on-write page and end anywhere in the bucket; attention
    still runs each span position over the slot's FULL gathered logical row
    (shared prefix pages + the span itself) with a causal offset. int8
    pages follow the decode-write discipline: a page whose offset-0
    position falls inside the span is claimed by that token's absmax,
    earlier (copied/pre-owned) pages keep their scale and the span's writes
    into them saturate against it. One program compiles per distinct span
    length C (write_start is traced); the engine accepts the retrace — a
    cache-hit tail is exactly the work the hit did NOT save."""
    _, C = input_ids.shape
    page = pool["k"].shape[2]
    hd = cfg.head_dim
    dt = cfg.dtype
    quant = pool["k"].dtype == jnp.int8

    mask = attention_mask.astype(jnp.int32)
    kv_mask = jax.lax.dynamic_update_slice(kv_mask, mask, (slot, write_start))
    lmax = kv_mask.shape[1]
    row_mask = jax.lax.dynamic_slice(kv_mask, (slot, 0), (1, lmax))

    w_pos = write_start + jnp.arange(C)              # [C] logical positions
    w_page = page_table_row[w_pos // page]           # [C] physical pages
    w_off = w_pos % page                             # [C] offsets within
    # index (within the span) of each token's page-offset-0 position:
    # >= 0 iff the page is CLAIMED by this span (its first position is
    # ours to write), < 0 for the fork page the span enters mid-way
    first_idx = w_pos - w_off - write_start          # [C] signed
    in_span = (first_idx >= 0)[:, None]              # [C, 1]
    first_idx = jnp.clip(first_idx, 0, C - 1)

    x = llama.embed(params, input_ids, cfg)
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta,
                            dtype=cfg.dtype)
    xs = ((params["layers"], pool["k"], pool["v"], pool["k_scale"],
           pool["v_scale"]) if quant
          else (params["layers"], pool["k"], pool["v"]))

    def write(pk, sk, kv):
        # kv: [C, kv_h, hd] — the span's freshly computed k or v rows
        if sk is None:
            return pk.at[w_page, w_off].set(kv), None
        amax = _block_amax(kv, axes=-1)                        # [C, kvh]
        # duplicate page indices in the scatter below all carry the SAME
        # scale value (claimed pages: their offset-0 token's absmax;
        # entered-mid-page pages: the existing scale), so write order
        # within the scatter cannot matter
        scale = jnp.where(in_span, amax[first_idx],
                          jnp.maximum(sk[w_page], _SCALE_FLOOR))
        sk = sk.at[w_page].set(scale)
        pk = pk.at[w_page, w_off].set(quant_page_block(kv, scale[:, :, None]))
        return pk, sk

    def body(h, xs):
        if quant:
            layer, pk, pv, sk, sv = xs
        else:
            (layer, pk, pv), sk, sv = xs, None, None
        b, s, d = h.shape
        hidden = rms_norm(h, layer["input_norm"], cfg.rms_norm_eps)
        q = (hidden @ layer["attn"]["wq"].astype(dt)).reshape(b, s, -1, hd)
        k = (hidden @ layer["attn"]["wk"].astype(dt)).reshape(b, s, -1, hd)
        v = (hidden @ layer["attn"]["wv"].astype(dt)).reshape(b, s, -1, hd)
        q, k = apply_rope(q, k, cos, sin)

        pk, sk = write(pk, sk, k[0])
        pv, sv = write(pv, sv, v[0])

        gk, gv = _gather_pages(pk, pv, sk, sv, page_table_row[None], dt)
        attn_out = attention(q, gk, gv, row_mask, causal=True,
                             q_offset=write_start)
        attn_out = attn_out.reshape(b, s, -1) @ layer["attn"]["wo"].astype(dt)
        h = llama.mlp_block(layer, h + attn_out, cfg)
        return h, ((pk, pv, sk, sv) if quant else (pk, pv))

    x, new = jax.lax.scan(body, x, xs)
    x = llama.final_norm(params, x[:, -1:, :], cfg)
    logits = llama.lm_head(params, x, cfg)
    new_pool = {"k": new[0], "v": new[1]}
    if quant:
        new_pool["k_scale"], new_pool["v_scale"] = new[2], new[3]
    return {"logits": logits[:, -1], "pool": new_pool, "kv_mask": kv_mask}

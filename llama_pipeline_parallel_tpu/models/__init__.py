from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig  # noqa: F401

"""The trainer: config -> mesh -> model -> data -> jitted step loop.

Re-implements the reference's `main()` + `train()` orchestration
(reference trainer_base_ds_mp.py:124-459) on the TPU-native stack:

- runtime schedule-total injection (reference :263-275): t_total is computed
  from dataset length x epochs unless `max_steps` is given;
- warm start from a converted checkpoint via `model_name_or_path`
  (reference :284 `load_module_only=True`);
- resume detection from `checkpoint-N` dirs (reference :451-455); the
  reference's dataloader fast-forward replay (:345-351) is replaced by O(1)
  repositioning from the checkpoint's data_state (docs/RESILIENCE.md
  "Elastic resume");
- periodic save every `save_steps` + final save (reference :367-371);
- rank-0 logging of lr / windowed mean loss every `logging_steps`
  (reference :360-374), extended with tokens/sec and MFU.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Any, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from llama_pipeline_parallel_tpu.ckpt.checkpoint import (
    CheckpointCorruptError,
    CheckpointManager,
)
from llama_pipeline_parallel_tpu.data.collator import (
    CausalLMCollator,
    PackedCausalLMCollator,
    PretokenizedCollator,
)
from llama_pipeline_parallel_tpu.data.datasets import SyntheticDataset
from llama_pipeline_parallel_tpu.data.loader import (
    DataLoader,
    PrefetchIterator,
    RepeatingLoader,
)
from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
from llama_pipeline_parallel_tpu.optim import OptimizerConfig, make_optimizer
from llama_pipeline_parallel_tpu.parallel import pipeline as pl
from llama_pipeline_parallel_tpu.parallel import train_step as ts
from llama_pipeline_parallel_tpu.parallel.distributed import (
    barrier,
    form_global_batch,
    host_dp_shard,
    initialize_distributed,
    set_barrier_timeout,
)
from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh
from llama_pipeline_parallel_tpu.utils import (
    faults,
    memwatch as memwatch_mod,
    numerics,
    perf,
    profiler as profiler_mod,
    timeline as timeline_mod,
    trace,
)
from llama_pipeline_parallel_tpu.utils.config import instantiate
from llama_pipeline_parallel_tpu.utils.logging import get_logger
from llama_pipeline_parallel_tpu.utils.metrics import (
    MetricsWriter,
    NullMetricsWriter,
    Throughput,
)

logger = get_logger(__name__)

_PRESETS = {
    "tiny": LlamaConfig.tiny,
    "llama_7b": LlamaConfig.llama_7b,
    "llama_13b": LlamaConfig.llama_13b,
    "llama_33b": LlamaConfig.llama_33b,
    "llama_65b": LlamaConfig.llama_65b,
    "llama2_7b": LlamaConfig.llama2_7b,
    "llama2_13b": LlamaConfig.llama2_13b,
    "llama2_70b": LlamaConfig.llama2_70b,
    "codellama_34b_16k": LlamaConfig.codellama_34b_16k,
}


def build_model_config(node: dict) -> LlamaConfig:
    node = dict(node)
    if "_target_" in node:
        return instantiate(node)
    preset = node.pop("preset", None)
    dtype = node.pop("dtype", None)
    if dtype is not None:
        node["dtype"] = jnp.dtype(dtype).type if isinstance(dtype, str) else dtype
    if preset is not None:
        return _PRESETS[preset](**node)
    return LlamaConfig(**node)


def _packing_factor(cfg: dict) -> int:
    """The one place packing_factor is parsed (train + eval + collator
    construction must agree on it)."""
    return int(cfg.get("packing_factor", 1) or 1)


def _virtual_stages(cfg: dict) -> int:
    """The `virtual_stages` knob (interleaved 1F1B / zb1,
    docs/SCHEDULES.md), parsed in one place so trainer + preflight +
    manifest agree on it."""
    v = int(cfg.get("virtual_stages", 1) or 1)
    if v > 1 and cfg.get("pipeline_schedule", "1f1b") not in (
            "interleaved_1f1b", "zb1", "solver"):
        raise ValueError(
            f"virtual_stages={v} requires pipeline_schedule: "
            f"interleaved_1f1b, zb1, or solver (got "
            f"{cfg.get('pipeline_schedule', '1f1b')!r})")
    return v


def _load_unit_schedule(cfg: dict) -> "Any":
    """The `schedule_file` key under `pipeline_schedule: solver`: a
    parallel/schedule.py unit-sequence JSON (emitted by
    `tools/preflight.py --select --emit-schedule <path>`), loaded and
    validated here so trainer + preflight share one loader. Returns None
    for the named schedules (they generate their canonical sequences
    internally)."""
    if cfg.get("pipeline_schedule", "1f1b") != "solver":
        if cfg.get("schedule_file"):
            raise ValueError(
                "schedule_file only applies under pipeline_schedule: solver "
                f"(got {cfg.get('pipeline_schedule', '1f1b')!r})")
        return None
    path = cfg.get("schedule_file")
    if not path:
        raise ValueError(
            "pipeline_schedule: solver needs schedule_file: <path> — emit "
            "one with `python tools/preflight.py --config ... --select "
            "--emit-schedule <path>` (docs/SCHEDULES.md 'Solver schedules')")
    from llama_pipeline_parallel_tpu.parallel import schedule as usched

    return usched.load(path)


def _offload_flags(cfg: dict) -> tuple[bool, bool]:
    """The `offload.*` config block (host-DRAM residual tiering,
    docs/SCHEDULES.md "Host offload"), parsed in one place so trainer +
    preflight agree: `wgrad_stash` tiers the zb1 W queue, `activations`
    the schedules' stage-input ring buffer (utils/host_stash.py)."""
    node = cfg.get("offload") or {}
    if not isinstance(node, dict):
        raise ValueError(
            f"offload must be a mapping of tier knobs, e.g. "
            f"offload: {{wgrad_stash: true}} — got {node!r}")
    known = {"wgrad_stash", "activations"}
    unknown = set(node) - known
    if unknown:
        raise ValueError(f"unknown offload.* key(s) {sorted(unknown)}; "
                         f"known: {sorted(known)}")
    return (bool(node.get("wgrad_stash", False)),
            bool(node.get("activations", False)))


def _kernel_flags(cfg: dict) -> tuple[bool, bool]:
    """The `kernels.*` config block (fused Pallas TPU kernels,
    docs/KERNELS.md), parsed in one place so trainer + preflight agree:
    `ce` selects the loss head's backend, `prologue` the decoder layers'
    rms_norm->RoPE->QKV prologue. Values are `xla` (default) or `pallas`;
    unknown keys/values are rejected like `offload.*`."""
    node = cfg.get("kernels") or {}
    if not isinstance(node, dict):
        raise ValueError(
            f"kernels must be a mapping of op backends, e.g. "
            f"kernels: {{ce: pallas}} — got {node!r}")
    known = {"ce", "prologue"}
    unknown = set(node) - known
    if unknown:
        raise ValueError(f"unknown kernels.* key(s) {sorted(unknown)}; "
                         f"known: {sorted(known)}")
    flags = []
    for key in ("ce", "prologue"):
        val = node.get(key, "xla")
        if val not in ("xla", "pallas"):
            raise ValueError(f"kernels.{key} must be 'xla' or 'pallas', "
                             f"got {val!r}")
        flags.append(val == "pallas")
    return tuple(flags)


def _offload_static(pcfg: "pl.PipelineConfig", mb_rows: int,
                    local_seqlen: int, hidden_size: int,
                    dtype_bytes: int) -> dict:
    """Run-constant host-stash telemetry for the metrics line AND
    health.json (docs/OBSERVABILITY.md): which residual stores are tiered
    and how many GiB of them are resident in host DRAM. Empty with offload
    off — no always-zero columns, the wgrad_queue_depth policy."""
    wgrad_off = pl.wgrad_offloaded_units(pcfg)
    wgrad_name = "wgrad_stash"
    if pcfg.schedule == "solver" and wgrad_off:
        # selective per-unit offload: name how many of the flush's units
        # tier (the all-True vector reads like the legacy boolean)
        total = pcfg.unit_schedule.n_units
        if wgrad_off < total:
            wgrad_name = f"wgrad_stash[{wgrad_off}/{total}]"
    tiers = [name for name, on in ((wgrad_name, wgrad_off > 0),
                                   ("activations", pcfg.offload_activations))
             if on]
    if not tiers:
        return {}
    resident = pl.host_stash_bytes(pcfg, mb_rows, local_seqlen, hidden_size,
                                   dtype_bytes)
    return {"offload_stash": "+".join(tiers),
            # 6 decimals: KiB resolution, so tiny-model smoke runs still
            # report a nonzero residency
            "offload_stash_resident_gib": round(resident / (1 << 30), 6)}


def _make_observatory(cfg: dict, pcfg: "pl.PipelineConfig", output_dir: str,
                      stash_bytes: int | None = None) -> tuple:
    """The observatory's run-scoped pieces (docs/OBSERVABILITY.md): the
    measured timeline driver (`timeline.*` config block — opt-in, blocks
    on every step's loss when on), the triggered profiler (`profiler.*`
    block — bounded capture windows on at_step / step-time z-score /
    numerics-anomaly triggers), and the memory watch (`memory.*` block —
    opt-in compiled-analysis capture + live per-step sampler; OFF
    compiles and samples nothing). One construction for both optimizer
    paths; `stash_bytes` is the host-stash resident estimate the
    sampler's rows carry next to the device/host polls."""
    tcfg = timeline_mod.TimelineConfig.from_cfg(cfg.get("timeline"))
    step_tl = None
    if tcfg.enabled:
        step_tl = timeline_mod.StepTimeline(
            pcfg, output_dir, write=jax.process_index() == 0,
            window=tcfg.window)
        logger.info(
            "timeline enabled: per-segment boundary marks compiled into "
            "the step, every step's loss fetch blocks (timeline.jsonl; "
            "docs/OBSERVABILITY.md 'Timelines')")
    pcap = profiler_mod.CaptureConfig.from_cfg(cfg.get("profiler"))
    if pcap is None:
        # no `profiler:` block arms ONLY the fleet trigger-file surface
        # (docs/OBSERVABILITY.md "Fleet"): z-score/at_step captures stay
        # off, but a fleet alert can still reach in for a bounded trace
        pcap = profiler_mod.CaptureConfig(zscore=0.0, on_anomaly=False)
    prof = (profiler_mod.TriggeredProfiler(pcap, output_dir)
            if jax.process_index() == 0 else None)
    mcfg = memwatch_mod.MemoryConfig.from_cfg(cfg.get("memory"))
    mem_watch = None
    if mcfg.enabled:
        mem_watch = memwatch_mod.MemoryWatch(
            output_dir, every=mcfg.every, top_buffers=mcfg.top_buffers,
            write=jax.process_index() == 0,
            stash_bytes=stash_bytes or None)
        logger.info(
            "memory watch enabled: compiled memory_analysis captured per "
            "program, live sampler every %d step(s) (memory.jsonl; "
            "docs/OBSERVABILITY.md 'Memory')", mcfg.every)
    return step_tl, prof, mem_watch


def _write_perf_rows(cfg: dict, pcfg: "pl.PipelineConfig", output_dir: str,
                     step_tl, mem_watch=None) -> None:
    """Close the run into the perf ledger (utils/perf.py): the analytic
    bubble next to its timeline-measured counterpart plus the rolling
    step-time percentiles, and — with the memory watch on — the
    compiled-vs-live memory rows (`mem_peak_gib`,
    `compiled_peak_gib:<label>`) — the trainer's contribution to the
    model-vs-measured calibration table tools/perf_report.py renders."""
    if (step_tl is None and mem_watch is None) or jax.process_index() != 0:
        return
    rows = []
    if step_tl is not None:
        rows.append(perf.make_row(
            "bubble_fraction", model=pl.bubble_fraction(pcfg),
            measured=step_tl.measured_bubble_median(), source="train",
            run=output_dir, schedule=pcfg.schedule,
            virtual_stages=pcfg.virtual_stages))
        sc = step_tl.scalars()
        if "step_time_p50" in sc:
            rows.append(perf.make_row(
                "step_time_s", measured=sc["step_time_p50"], unit="s",
                source="train", run=output_dir, p95=sc.get("step_time_p95")))
        peak_bytes, src = trace.device_peak_bytes()
        if peak_bytes is not None and src == "device":
            rows.append(perf.make_row(
                "peak_gib", measured=peak_bytes / (1 << 30), unit="GiB",
                source="train", run=output_dir))
    if mem_watch is not None:
        rows.extend(mem_watch.perf_rows(run=output_dir))
    perf.append_rows(os.path.join(output_dir, "perf.jsonl"), rows)


def _schedule_static_scalars(pcfg: "pl.PipelineConfig") -> dict:
    """Run-constant schedule telemetry repeated on every metrics line
    (docs/OBSERVABILITY.md): the schedule name, its analytic bubble
    fraction, and — under zb1 — the peak W-queue occupancy of the split
    backward (0 elsewhere; omitted rather than an always-zero column)."""
    out = {"schedule": pcfg.schedule,
           "bubble_fraction": round(pl.bubble_fraction(pcfg), 4)}
    if pl.wgrad_queue_peak(pcfg):
        out["wgrad_queue_depth"] = pl.wgrad_queue_peak(pcfg)
    return out


def _schedule_health_static(pcfg: "pl.PipelineConfig", topology: dict) -> dict:
    """The static health.json payload: the topology block (whose `schedule`
    field the elastic-restore contract records) plus, under zb1, the same
    wgrad_queue_depth the metrics line carries — one construction for both
    optimizer paths so the two sinks can never desynchronize."""
    out = {"topology": topology}
    if pl.wgrad_queue_peak(pcfg):
        out["wgrad_queue_depth"] = pl.wgrad_queue_peak(pcfg)
    return out


def build_manifest(cfg: dict, model_cfg: LlamaConfig, pp: int) -> StageManifest:
    """Stage partition policy, shared by the trainer and tools/preflight.py
    (the preflight must compile the SAME program the trainer runs): explicit
    per-stage layer_counts > cost-balanced (`stage_balance: cost`, the
    SURVEY §7.3-item-2 MFU lever) > even split. Indivisible layer counts
    fall back to cost-balanced automatically. `virtual_stages` > 1
    (interleaved 1F1B / zb1) switches to the round-robin chunked layout —
    it rejects uneven partitions (manifest.py), so layer_counts/
    stage_balance cannot be combined with it."""
    v = _virtual_stages(cfg)
    if v > 1:
        if cfg.get("layer_counts") or cfg.get("stage_balance", "even") == "cost":
            raise ValueError(
                "virtual_stages > 1 (interleaved 1F1B / zb1) uses the "
                "round-robin even chunk partition; layer_counts/"
                "stage_balance: cost cannot apply — drop them or fall back "
                "to a flat schedule")
        return StageManifest.for_config(model_cfg, pp, virtual_stages=v)
    if cfg.get("layer_counts"):
        return StageManifest(num_layers=model_cfg.num_hidden_layers,
                             num_stages=pp,
                             layer_counts=tuple(cfg["layer_counts"]))
    if (cfg.get("stage_balance", "even") == "cost"
            or model_cfg.num_hidden_layers % pp):
        manifest = StageManifest.balanced(model_cfg, pp)
        logger.info("stage partition (cost-balanced): %s",
                    manifest.stage_layer_counts)
        return manifest
    return StageManifest.for_config(model_cfg, pp)


def build_pipeline_config(cfg: dict, mesh_cfg: Any, manifest: StageManifest
                          ) -> "pl.PipelineConfig":
    """PipelineConfig from the run config — one construction for the trainer
    and tools/preflight.py."""
    offload_wgrad, offload_acts = _offload_flags(cfg)
    kernel_ce, kernel_prologue = _kernel_flags(cfg)
    return pl.PipelineConfig(
        num_stages=mesh_cfg.pp,
        unit_schedule=_load_unit_schedule(cfg),
        num_microbatches=cfg.get("gradient_accumulation_steps", 1),
        remat=cfg.get("activation_checkpointing", True),
        remat_policy=cfg.get("remat_policy", "nothing_saveable"),
        schedule=cfg.get("pipeline_schedule", "1f1b"),
        virtual_stages=manifest.virtual_stages,
        accum_chunks=cfg.get("gradient_accumulation_chunks", 1),
        sequence_parallel=cfg.get("sequence_parallel", "ring"),
        loss_chunks=cfg.get("loss_vocab_chunks", 1),
        layer_counts=None if manifest.is_even else manifest.stage_layer_counts,
        packed=_packing_factor(cfg) > 1,
        offload_wgrad=offload_wgrad,
        offload_activations=offload_acts,
        kernel_ce=kernel_ce,
        kernel_prologue=kernel_prologue)


def build_dataset_and_collator(cfg: dict, model_cfg: LlamaConfig) -> tuple[Any, Any]:
    packing = _packing_factor(cfg)
    data_cfg = cfg.get("dataset")
    if data_cfg is None or data_cfg.get("synthetic"):
        if packing > 1:
            raise ValueError("packing_factor requires a tokenizer-backed "
                             "dataset (the synthetic dataset emits fixed "
                             "full-length rows — nothing to pack)")
        seq = (data_cfg or {}).get("seq_length", cfg.get("max_seq_length", 512))
        ds = SyntheticDataset(
            vocab_size=model_cfg.vocab_size, seq_length=seq,
            pseudo_dataset_len=(data_cfg or {}).get("pseudo_dataset_len", 4096),
            seed=cfg.get("seed", 42),
            pad_fraction=(data_cfg or {}).get("pad_fraction", 0.0))
        return ds, PretokenizedCollator()
    ds = instantiate(data_cfg)
    coll_cfg = cfg.get("collator")
    if coll_cfg is not None and "_target_" in coll_cfg:
        if packing > 1:
            raise ValueError("packing_factor cannot be combined with a "
                             "custom collator _target_; construct "
                             "PackedCausalLMCollator there directly")
        collator = instantiate(coll_cfg)
    else:
        from transformers import AutoTokenizer

        from llama_pipeline_parallel_tpu.data.tokenization import expand_special_tokenizer

        tokenizer = AutoTokenizer.from_pretrained(cfg["tokenizer_path"])
        expand_special_tokenizer(tokenizer)
        if len(tokenizer) > model_cfg.vocab_size:
            raise ValueError(
                f"tokenizer has {len(tokenizer)} tokens but model vocab_size is "
                f"{model_cfg.vocab_size}; re-convert the checkpoint with vocab "
                f"expansion (tools/convert_hf.py resizes embeddings, like "
                f"reference convert2ckpt.py:60-63)")
        if packing > 1:
            collator = PackedCausalLMCollator(
                tokenizer, cfg.get("max_seq_length", 512), pack_factor=packing)
        else:
            collator = CausalLMCollator(tokenizer, cfg.get("max_seq_length", 512))
    return ds, collator


def _flash_without_mask(q, k, v, padding_mask=None, *, causal=True):
    """flash_attention minus the segment-mask input streams (see
    select_attention.finish)."""
    from llama_pipeline_parallel_tpu.ops.flash_attention import flash_attention

    return flash_attention(q, k, v, None, causal=causal)


_AUTO_ATTN_CACHE: dict = {}


def _measure_segments(batch: int, seq_len: int) -> jnp.ndarray:
    """Representative packed-row segment ids for the auto measurement: four
    equal segments covering ~4/5 of the row, then a genuine pad tail — so
    the timing includes the kernels' fully-masked-pad skip path the real
    packed run hits."""
    seg = np.zeros((batch, seq_len), np.int32)
    fifth = max(seq_len // 5, 1)
    for i in range(4):
        seg[:, i * fifth:(i + 1) * fifth] = i + 1
    return jnp.asarray(seg)


def _measure_attention(model_cfg: LlamaConfig, seq_len: int,
                       micro_batch: int = 1, packed: bool = False) -> Any:
    """Time exact vs flash (fwd+bwd, jitted, value-fetch barrier) at this
    run's ACTUAL (microbatch, seq) shape ON THE DEVICE — with segment-id
    streams when the run packs sequences, since those change the flash
    kernel's work — and return the faster. `auto` picks by measurement, not
    by threshold folklore. Cached per shape; any failure falls back to the
    exact path."""
    from llama_pipeline_parallel_tpu.ops.attention import attention
    from llama_pipeline_parallel_tpu.ops.flash_attention import flash_attention

    key = (seq_len, micro_batch, packed, model_cfg.num_attention_heads,
           model_cfg.kv_heads, model_cfg.head_dim)
    if key in _AUTO_ATTN_CACHE:
        return _AUTO_ATTN_CACHE[key]

    def measure_locally():
        import time

        try:
            rng = np.random.RandomState(0)
            h, hkv, hd = (model_cfg.num_attention_heads, model_cfg.kv_heads,
                          model_cfg.head_dim)
            b = max(int(micro_batch), 1)
            q = jnp.asarray(rng.randn(b, seq_len, h, hd), jnp.bfloat16)
            k = jnp.asarray(rng.randn(b, seq_len, hkv, hd), jnp.bfloat16)
            v = jnp.asarray(rng.randn(b, seq_len, hkv, hd), jnp.bfloat16)
            mask = _measure_segments(b, seq_len) if packed else None

            def time_one(fn):
                loss = lambda q, k, v: (fn(q, k, v, mask, causal=True)
                                        .astype(jnp.float32) ** 2).sum()
                step = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
                float(step(q, k, v)[0])  # compile + barrier (value fetch)
                t0 = time.perf_counter()
                for _ in range(3):
                    float(step(q, k, v)[0])
                return (time.perf_counter() - t0) / 3

            t_exact, t_flash = time_one(attention), time_one(flash_attention)
            winner = flash_attention if t_flash < t_exact else attention
            logger.info("attention=auto @ batch %d seq %d packed=%s: "
                        "exact %.2fms, flash %.2fms -> %s",
                        b, seq_len, packed, 1e3 * t_exact, 1e3 * t_flash,
                        "flash" if winner is flash_attention else "exact")
            return winner
        except Exception as e:
            logger.warning("attention=auto measurement failed (%r); using exact", e)
            return attention

    if jax.process_count() > 1:
        # Every process must compile the SAME program: near-equal timings (or
        # a one-host measurement failure) must not let hosts pick different
        # kernels — process 0 measures, everyone takes its verdict.
        from jax.experimental import multihost_utils

        choice = 0
        if jax.process_index() == 0:
            choice = 1 if measure_locally() is flash_attention else 0
        choice = int(multihost_utils.broadcast_one_to_all(np.int32(choice)))
        winner = flash_attention if choice else attention
    else:
        winner = measure_locally()
    _AUTO_ATTN_CACHE[key] = winner
    return winner


def select_attention(impl: str, seq_length: int, mesh,
                     sequence_parallel: str = "ring",
                     model_cfg: LlamaConfig | None = None,
                     packed: bool = False,
                     micro_batch: int = 1) -> Any:
    """'exact' | 'flash' | 'auto'. The reference tried and failed to enable
    flash attention (README.md:141-143); here `auto` MEASURES both paths on
    the device at the run's (microbatch, seq) shape — with segment streams
    when packed — and keeps the faster.

    `seq_length` must be the ACTUAL batch sequence length (probe the
    collator), not a config guess. The flash kernel's tiling rule is
    adaptive (ops/flash_attention.py `_auto_block`: the largest 128-multiple
    <= 1024 that divides the length): seq 1536 tiles with 768 blocks, 1280
    with 640; only lengths no 128-multiple divides need the exact path.
    Checked against the length the kernel actually SEES, which under ring
    sequence parallelism is the per-slab seq/sp (Ulysses re-shards to the
    full sequence, so there it stays seq)."""
    from llama_pipeline_parallel_tpu.ops.attention import attention
    from llama_pipeline_parallel_tpu.ops.flash_attention import (
        _auto_block,
        flash_attention,
    )

    def finish(fn):
        """Unpacked single-chip-sequence flash runs skip the kernel's segment
        streams: a 0/1 mask is a documented no-op there, and dropping it
        keeps the non-packed hot path identical to the pre-segments kernel.
        Not applied under sp>1 — make_sp_attention dispatches its ring
        backend by `inner_attn is flash_attention` identity, and ring drops
        the mask itself anyway."""
        if fn is flash_attention and not packed and mesh.shape["sp"] == 1:
            return _flash_without_mask
        return fn

    if impl == "exact":
        return attention
    if impl == "flash":
        return finish(flash_attention)
    if impl == "auto":
        sp = mesh.shape["sp"]
        kernel_len = seq_length // sp if (sp > 1 and sequence_parallel == "ring") \
            else seq_length
        on_tpu = mesh.devices.ravel()[0].platform == "tpu"
        tiles = kernel_len % _auto_block(kernel_len) == 0
        if not on_tpu:
            return attention  # flash interpret mode off-TPU is far slower
        if not tiles:
            logger.warning(
                "attention=auto: kernel sequence length %d (seq %d / sp slab) "
                "is not divisible by any 128-multiple block <= 1024; using "
                "the exact path (pad to a 128 multiple to enable flash)",
                kernel_len, seq_length)
            return attention
        if model_cfg is None:
            return finish(flash_attention) if kernel_len >= 2048 else attention
        return finish(_measure_attention(model_cfg, kernel_len,
                                         micro_batch=micro_batch, packed=packed))
    raise ValueError(f"unknown attention impl {impl!r} (use exact|flash|auto)")


# Preemption state shared between the signal handlers (installed at trainer
# entry, BEFORE jax.distributed.initialize) and the step loop. Module-level so
# a signal landing during the minutes of setup/compile is still seen when the
# loop finally starts. Mutated ONLY from the main thread (install/release
# guard) and the signal handler, which also runs in the main thread.
_STOP_SIGNALS: list[int] = []
_INSTALLED_SIGNALS: list[int] = []
_PREVIOUS_HANDLERS: dict = {}
_NOTIFIER_PROBE_FAILED = False  # warn-once latch, see _cpp_notifier_owns_sigterm


def _in_main_thread() -> bool:
    import threading

    return threading.current_thread() is threading.main_thread()


def _on_preemption_signal(sig, frame):
    _STOP_SIGNALS.append(sig)
    # async-signal-safe notice — without it a Ctrl+C during minutes of
    # setup/compile looks ignored (the stop only happens at the next step)
    os.write(2, b"\n[trainer] signal received; will checkpoint at the next "
                b"step and exit (signal again to force-quit)\n")
    # restore defaults so a second Ctrl+C force-quits a wedged save — but
    # only for the signals WE still own: SIGTERM passes to the C++ notifier
    # when jax.distributed initializes AFTER the install, and writing its
    # sigaction then would disable the pod-wide preemption protocol
    for s in _INSTALLED_SIGNALS:
        if s == signal.SIGTERM and _cpp_notifier_owns_sigterm():
            continue
        signal.signal(s, signal.SIG_DFL)


def _cpp_notifier_owns_sigterm() -> bool:
    """True iff jax's C++ preemption notifier holds the SIGTERM sigaction.

    The notifier is registered with the preemption SYNC MANAGER, not the
    bare distributed client: `jax.distributed.initialize()` skips it when
    `jax_enable_preemption_service=False`, and then Python must keep owning
    SIGTERM even though a client is active.

    Reads a jax internal and is called from inside signal handlers, so it
    must never raise: if a JAX upgrade moves the attribute, fall back to
    False (= Python keeps SIGTERM — the pre-init behavior) and warn once —
    via os.write, not logging: the logging stack is not async-signal-safe
    (a signal landing mid-emit would re-enter a buffered writer), the same
    rule _on_preemption_signal follows."""
    try:
        from jax._src import distributed as jax_distributed

        return jax_distributed.global_state.preemption_sync_manager is not None
    except (ImportError, AttributeError):  # jax internal moved
        global _NOTIFIER_PROBE_FAILED
        if not _NOTIFIER_PROBE_FAILED:
            _NOTIFIER_PROBE_FAILED = True
            os.write(2, b"WARNING: jax._src.distributed.global_state."
                        b"preemption_sync_manager not found (jax internals "
                        b"changed); assuming Python owns SIGTERM - pod "
                        b"preemption now relies on the Python handlers\n")
        return False


def _install_preemption_handlers() -> None:
    """Record SIGTERM/SIGINT — the TPU-VM maintenance-event notice — from the
    very start of the run. Must run before `jax.distributed.initialize`: on a
    pod the runtime's C++ preemption notifier takes SIGTERM over from Python
    (preemption_notifier.cc registers its own sigaction), after which the
    signal is only observable through the coordination service's sync point
    (`_preemption_notice`); these Python handlers cover the pre-init window
    and all single-process runs.

    If a caller initialized jax.distributed BEFORE calling run_training, the
    notifier already owns SIGTERM and taking it back would silently disable
    the coordination-service protocol pod-wide — leave it alone and own only
    SIGINT there.

    A run on a worker thread (embedded caller) installs nothing and must not
    touch the module state — it may belong to a concurrent main-thread run."""
    if not _in_main_thread():
        return
    signals = [signal.SIGINT] if _cpp_notifier_owns_sigterm() \
        else [signal.SIGTERM, signal.SIGINT]
    _STOP_SIGNALS.clear()  # a stale flag from a prior run must not stop this one
    for sig in signals:
        prev = signal.signal(sig, _on_preemption_signal)
        # a None "previous" is a sigaction installed by non-Python code —
        # signal.signal can't reinstate it; record SIG_DFL so the restore
        # path never leaves OUR handler dangling after the run
        _PREVIOUS_HANDLERS[sig] = signal.SIG_DFL if prev is None else prev
        _INSTALLED_SIGNALS.append(sig)


def _release_preemption_handlers() -> None:
    """Restore the pre-run handlers. Idempotent (second call is a no-op), so
    _train_loop can hand the signals back before the final save — a Ctrl+C
    there must interrupt, not be swallowed by handlers nothing re-checks —
    and run_training's finally stays the backstop for every other exit."""
    if not _in_main_thread():
        return
    for sig, handler in list(_PREVIOUS_HANDLERS.items()):
        # never restore over the C++ notifier's SIGTERM sigaction — it must
        # keep feeding the coordination service for later runs in this process
        if not (sig == signal.SIGTERM and _cpp_notifier_owns_sigterm()):
            signal.signal(sig, handler)
        del _PREVIOUS_HANDLERS[sig]
    _STOP_SIGNALS.clear()
    _INSTALLED_SIGNALS.clear()


def _reset_compilation_cache() -> None:
    """Re-initialize jax's persistent compile cache so a mid-process
    jax_compilation_cache_dir change takes effect. Best-effort: the helper
    is a jax-internal module, and a miss only costs cache reuse."""
    try:
        from jax.experimental.compilation_cache import compilation_cache

        compilation_cache.reset_cache()
    except Exception as e:  # jax internals moved — keep training
        logger.warning("could not reset the XLA compile cache (%r); the "
                       "compilation_cache_dir change may not apply to this "
                       "process", e)


def run_training(cfg: dict) -> dict:
    """The full training run; returns a summary dict for programmatic callers."""
    _install_preemption_handlers()
    # Fault-tolerance wiring (docs/RESILIENCE.md): the env plan wins over the
    # config node — the supervisor drives chaos runs through LPT_FAULT_PLAN
    # and must be able to override whatever the config ships.
    if os.environ.get(faults.ENV_PLAN):
        faults.configure_from_env()
    else:
        faults.configure(cfg.get("fault_plan"))
    set_barrier_timeout(cfg.get("barrier_timeout_s"))
    # jax settings are process-global: save/restore around the run so a later
    # run_training in the same process doesn't inherit this config's cache
    prev_cache = jax.config.jax_compilation_cache_dir
    if cfg.get("compilation_cache_dir"):
        # Persistent XLA compile cache: a 65B pipeline step costs minutes of
        # compile per topology; resumes/restarts on the same pod skip it.
        jax.config.update("jax_compilation_cache_dir",
                          str(cfg["compilation_cache_dir"]))
        # the cache object initializes lazily ONCE per process — if an earlier
        # run in this process already compiled anything, the dir change is
        # silently ignored until the cache is reset
        _reset_compilation_cache()
    try:
        return _run_training(cfg)
    finally:
        if cfg.get("compilation_cache_dir"):
            jax.config.update("jax_compilation_cache_dir", prev_cache)
            _reset_compilation_cache()  # later runs must not inherit the dir
        trace.configure(None)  # close this run's spans.jsonl writer
        set_barrier_timeout(None)  # later runs must not inherit the timeout
        faults.configure(None)  # ...or this run's fault plan
        _release_preemption_handlers()


def _run_training(cfg: dict) -> dict:
    seed = cfg.get("seed", 42)
    output_dir = cfg["output_dir"]

    initialize_distributed()  # no-op unless a pod coordinator is configured
    # Span stream from here on: everything until the step loop starts is the
    # `init` bucket (model build, checkpoint restore, first-batch probe).
    trace.configure(output_dir, write=jax.process_index() == 0)
    mesh_cfg = MeshConfig(**cfg.get("mesh", {}))
    mesh = make_mesh(mesh_cfg)
    model_cfg = build_model_config(cfg["model"])
    manifest = build_manifest(cfg, model_cfg, mesh_cfg.pp)
    # Packing composes with every parallelism axis: both attention backends
    # handle segment masks at sp=1 (the exact op's pairwise test, the flash
    # kernel's in-tile _seg_tile_mask); under sp>1 Ulysses all-gathers the
    # mask to full length and ring rotates the kv segment slab with its k/v
    # (pcfg.packed switches the ring's segment streams on).
    packing = _packing_factor(cfg)
    pcfg = build_pipeline_config(cfg, mesh_cfg, manifest)
    if (pcfg.offload_wgrad or pcfg.offload_activations
            or pl.wgrad_offloaded_units(pcfg)):
        from llama_pipeline_parallel_tpu.utils import host_stash

        logger.info(
            "host stash enabled (wgrad=%s activations=%s): %s",
            pcfg.offload_wgrad or pl.wgrad_offloaded_units(pcfg),
            pcfg.offload_activations,
            "pinned_host memory space — residuals tier to host DRAM"
            if host_stash.transfers_enabled() else
            "transfers gated off (no distinct host memory space on this "
            "backend, or LPT_HOST_STASH_FORCE=0) — same schedule, stores "
            "stay device-resident")
    if pcfg.kernel_ce or pcfg.kernel_prologue:
        logger.info(
            "pallas kernels enabled (ce=%s prologue=%s): %s (docs/KERNELS.md)",
            pcfg.kernel_ce, pcfg.kernel_prologue,
            "Mosaic-compiled" if jax.default_backend() == "tpu"
            else "interpret mode — parity semantics, no kernel speedup "
                 "off-TPU")
    topology = _topology_meta(mesh, pcfg, manifest)
    # Numerics observatory (docs/OBSERVABILITY.md "Numerics"): per-stage
    # training-dynamics stats computed in-graph, anomaly detection + the
    # numerics.jsonl stream on the host. On by default — the in-graph
    # reductions are a few hundred floats next to a pipeline step.
    ncfg = numerics.NumericsConfig.from_cfg(cfg.get("numerics"))
    if faults.has_rule("step", "grad_nonfinite"):
        if not ncfg.enabled:
            # the chaos op exists to exercise the observatory; without it
            # the poison would NaN the params with no guard/skip/record
            raise ValueError(
                "fault plan contains a grad_nonfinite rule but "
                "numerics.enabled is false — the nonfinite guard would be "
                "unarmed; enable numerics or drop the rule")
        bad = [s for s in faults.rule_field_values(
                   "step", "grad_nonfinite", "stage")
               if not 0 <= s < pcfg.num_stages]
        if bad:
            # an out-of-range stage would make the poison mask all-ones: the
            # drill "passes" while exercising nothing
            raise ValueError(
                f"grad_nonfinite rule stage(s) {bad} out of range for "
                f"num_stages={pcfg.num_stages}")
    monitor = (numerics.NumericsMonitor(output_dir, ncfg,
                                        write=jax.process_index() == 0,
                                        recorder=trace.recorder())
               if ncfg.enabled else None)

    dataset, collator = build_dataset_and_collator(cfg, model_cfg)
    micro_batch = cfg.get("per_device_train_batch_size", 1)
    # with packing, the loader feeds pack_factor x examples per emitted row
    per_replica_batch = micro_batch * pcfg.num_microbatches * packing
    data_node = cfg.get("data") or {}
    loader = DataLoader(dataset, collator, per_replica_batch=per_replica_batch,
                        dp_size=mesh_cfg.dp, seed=seed,
                        dp_range=host_dp_shard(mesh),
                        quarantine_bad_records=bool(
                            data_node.get("quarantine_bad_shards", False)),
                        # per-sample-id ledger (elastic-resume audits); the
                        # file covers THIS process's dp shards — process 0
                        # only, so a pod doesn't interleave writers
                        sample_ledger=(os.path.join(output_dir, "samples.jsonl")
                                       if data_node.get("log_sample_ids")
                                       and jax.process_index() == 0 else None))
    steps_per_epoch = len(loader)
    if steps_per_epoch == 0:
        raise ValueError(
            f"dataset of {len(dataset)} examples yields 0 steps at "
            f"dp={mesh_cfg.dp} x per_replica_batch={per_replica_batch}")

    # Runtime schedule-total injection (reference trainer_base_ds_mp.py:263-275).
    # `total_steps` (schedule horizon) is separate from `max_steps` (loop end)
    # so an interrupted-then-resumed run sees the same LR curve as an
    # uninterrupted one.
    epochs = cfg.get("num_train_epochs", 1)
    t_total = cfg.get("total_steps") or cfg.get("max_steps") or steps_per_epoch * epochs
    end_step = min(cfg.get("max_steps") or t_total, t_total)
    warmup = cfg.get("warmup_steps")
    if warmup is None:
        warmup = max(int(t_total * cfg.get("warmup_proportion", 0.0)), 1)
    ocfg = OptimizerConfig(
        learning_rate=cfg.get("learning_rate", 1e-6),
        weight_decay=cfg.get("weight_decay", 0.001),
        beta1=cfg.get("adam_beta1", 0.9), beta2=cfg.get("adam_beta2", 0.99),
        eps=cfg.get("adam_eps", 1e-8),
        max_grad_norm=cfg.get("max_grad_norm", 5.0),
        total_steps=t_total, warmup_steps=warmup)
    tx, schedule = make_optimizer(ocfg)

    # ---- params: fresh init, warm start, or resume ------------------------
    # Sharded init: each device materializes only its own stage/tp shard
    # (the reference's LayerSpec lazy construction, README.md:21-22).
    stacked_template = ts.init_params_sharded(
        jax.random.PRNGKey(seed), model_cfg, mesh, manifest)
    mgr = CheckpointManager(output_dir)

    if cfg.get("optimizer_offload"):
        return _run_offload(cfg, mesh, model_cfg, manifest, pcfg, ocfg,
                            dataset, collator, loader, end_step, stacked_template, mgr,
                            ncfg=ncfg, monitor=monitor)
    if cfg.get("optimizer_offload_zero2"):
        raise ValueError("optimizer_offload_zero2 requires optimizer_offload: "
                         "true (it shards the HOST-offloaded masters/grads "
                         "over dp; the fused optimizer already has ZeRO-1 "
                         "sharded moments)")

    resume_step = 0
    # Donate the init output into the train state (no second fp32 copy) and
    # keep only abstract shapes as the structure template from here on.
    template_struct = jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                                   stacked_template)
    state = ts.init_train_state(stacked_template, tx, mesh, donate_params=True)
    stacked_template = template_struct
    restored = (_restore_with_fallback(
        mgr, lambda s: mgr.load(s, state.params, state.opt_state, manifest))
        if cfg.get("resume", True) else None)
    if restored is not None:
        p, o, resume_step = restored
        shard_of = lambda tmpl: jax.tree.map(lambda x: x.sharding, tmpl)
        state = ts.TrainState(
            step=jnp.asarray(resume_step, jnp.int32),
            params=jax.device_put(p, shard_of(state.params)),
            opt_state=jax.device_put(o, shard_of(state.opt_state)))
        logger.info("resumed full state from checkpoint-%d", resume_step)
        _note_topology_change(mgr, resume_step, topology)
    elif cfg.get("model_name_or_path"):
        warm = CheckpointManager(cfg["model_name_or_path"])
        warm_step = warm.latest_step()
        if warm_step is None:
            raise FileNotFoundError(
                f"no checkpoint under model_name_or_path={cfg['model_name_or_path']} "
                f"(run tools/convert_hf.py first, like reference convert2ckpt.py)")
        p = warm.load_params(warm_step, state.params, manifest)
        state = ts.TrainState(
            step=state.step,
            params=jax.device_put(p, jax.tree.map(lambda x: x.sharding, state.params)),
            opt_state=state.opt_state)
        logger.info("warm-started module weights from %s", cfg["model_name_or_path"])

    seq_length = int(collator([dataset[0]])["input_ids"].shape[1])
    if seq_length % mesh_cfg.sp:
        raise ValueError(f"sequence length {seq_length} must divide into "
                         f"sp={mesh_cfg.sp} equal slabs")
    attn_fn = select_attention(cfg.get("attention", "auto"), seq_length, mesh,
                               sequence_parallel=cfg.get("sequence_parallel", "ring"),
                               model_cfg=model_cfg,
                               packed=_packing_factor(cfg) > 1,
                               micro_batch=micro_batch)
    # The poison input (the grad_nonfinite chaos op) is only compiled into
    # the step when the active fault plan carries such a rule — steady-state
    # runs keep the two-argument signature (no extra per-step H2D).
    poison_on = faults.has_rule("step", "grad_nonfinite")
    step_tl, prof, mem_watch = _make_observatory(
        cfg, pcfg, output_dir,
        stash_bytes=pl.host_stash_bytes(pcfg, *pl.stash_dims(
            micro_batch, seq_length, mesh_cfg.sp, model_cfg.hidden_size,
            model_cfg.dtype)))
    step_fn = ts.make_train_step(mesh, model_cfg, pcfg, tx, schedule,
                                 stacked_template, attn_fn=attn_fn,
                                 collect_stats=ncfg.enabled, poison=poison_on,
                                 # gpipe has no segments: marks stay out and
                                 # the timeline degrades to step-wall records
                                 timeline=step_tl is not None
                                 and step_tl.segmented)

    # ---- loop -------------------------------------------------------------
    state_box = [state]

    def do_step(batch, step, fault=None):
        gbatch = form_global_batch(mesh, batch)
        if mem_watch is not None and "train_step" not in mem_watch.compiled:
            # compile-time memory evidence (docs/OBSERVABILITY.md
            # "Memory"): AOT lowering reads only avals — no execution, no
            # donation — and the one extra compile is the watch's
            # documented ON cost, landing in the first step's compile
            # bucket. OFF never reaches this branch.
            try:
                args = ((state_box[0], gbatch, numerics.fault_stage(None))
                        if poison_on else (state_box[0], gbatch))
                mem_watch.note_compiled("train_step",
                                        step_fn.lower(*args).compile())
            except Exception as e:
                logger.debug("compiled memory capture failed: %r", e)
        if poison_on:
            new_state, metrics = step_fn(state_box[0], gbatch,
                                         numerics.fault_stage(fault))
        else:
            new_state, metrics = step_fn(state_box[0], gbatch)
        state_box[0] = new_state
        if monitor is not None:
            # async D2H enqueue + lag-1 processing; may raise
            # NonfiniteHaltError (handled by _train_loop's halt path)
            monitor.observe(step, metrics["loss"], metrics["grad_norm"],
                            metrics.get("numerics"))
        return metrics["loss"], lambda: {"lr": float(metrics["lr"]),
                                         "grad_norm": float(metrics["grad_norm"])}

    data_start = (_resume_data_position(mgr, resume_step, loader,
                                        len(dataset), seed)
                  if resume_step else (0, 0))
    # data-stream batches minus step count: nonzero only after a
    # changed-global-batch remap, and every LATER checkpoint must carry the
    # offset forward or a second resume re-trains the remapped span
    data_delta = (data_start[0] * max(len(loader), 1)
                  + data_start[1]) - resume_step

    def do_save(step, final=False):
        # async_save: periodic checkpoints return once Orbax holds host
        # copies; the disk flush + commit + off-node sync overlap the next
        # training steps. Final/preemption saves block — the process exits
        # right after, and a daemon commit thread would die with it.
        barrier("pre-save")
        mgr.save(step, state_box[0].params, manifest, model_cfg,
                 opt_state=state_box[0].opt_state,
                 blocking=final or not cfg.get("async_save", False),
                 on_complete=lambda path: _sync_checkpoint(cfg, path),
                 keep_last=cfg.get("save_total_limit"),
                 extra_meta={"topology": topology,
                             "data_state": _data_state(step, loader,
                                                       len(dataset), seed,
                                                       data_delta),
                             **_eval_meta()})

    do_eval = _make_evaluator(cfg, mesh, model_cfg, pcfg, stacked_template,
                              attn_fn, lambda: state_box[0].params)
    off_static = _offload_static(pcfg, *pl.stash_dims(
        micro_batch, seq_length, mesh_cfg.sp, model_cfg.hidden_size,
        model_cfg.dtype))
    try:
        final_loss, preempted_at = _train_loop(
            cfg, model_cfg, mesh, loader, seq_length,
            resume_step, end_step, do_step, do_save, do_eval,
            extra_scalars=_host_scalars(collator, loader),
            static_scalars={**_schedule_static_scalars(pcfg), **off_static},
            monitor=monitor, data_start=data_start,
            health_static={**_schedule_health_static(pcfg, topology),
                           **off_static},
            step_timeline=step_tl, profiler=prof, mem_watch=mem_watch)
    except BaseException:
        # join the in-flight commit, but never let ITS failure replace the
        # training exception that actually killed the run
        try:
            mgr.finalize()
        except Exception:
            logger.exception("async checkpoint commit also failed while "
                             "unwinding a training error")
        raise
    mgr.finalize()  # surface any async-commit failure on the clean path
    _write_perf_rows(cfg, pcfg, output_dir, step_tl, mem_watch)
    return _summarize(final_loss, preempted_at, end_step, steps_per_epoch,
                      output_dir)


def _topology_meta(mesh, pcfg: "pl.PipelineConfig",
                   manifest: StageManifest | None = None) -> dict:
    """The run's topology, recorded in every checkpoint's meta.json and in
    health.json — the source half of the elastic-restore contract
    (docs/RESILIENCE.md "Elastic resume"): a later incarnation on a
    different mesh reads it to explain (and log) what changed.

    `layer_counts` names the stage PARTITION — "even/10" or the explicit
    per-stage list — so a partition change (e.g. (4,4,4,1) -> even/2 from a
    generated-ladder resize) is logged like a pp/dp/tp change instead of
    silently resharding through the canonical layout."""
    mc = MeshConfig(pp=mesh.shape["pp"], dp=mesh.shape["dp"],
                    tp=mesh.shape["tp"], sp=mesh.shape["sp"])
    out = {"pp": mc.pp, "dp": mc.dp, "tp": mc.tp, "sp": mc.sp,
           "layout": mc.describe(),
           "schedule": pcfg.schedule, "virtual_stages": pcfg.virtual_stages,
           "process_count": jax.process_count()}
    if manifest is not None:
        out["layer_counts"] = (
            f"even/{manifest.stage_layer_counts[0]}" if manifest.is_even
            else list(manifest.stage_layer_counts))
    return out


def _data_state(step: int, loader: DataLoader, dataset_len: int,
                seed: int, batch_delta: int = 0) -> dict:
    """The sampler position at `step`, in dp-width-independent units: the
    epoch permutation is a function of (seed, epoch) only, and step b
    consumes exactly global-order positions [b*G, (b+1)*G) — so
    consumed_samples, not any per-replica cursor, is the canonical resume
    coordinate that survives a dp resize (docs/RESILIENCE.md).

    `batch_delta`: data-stream batches minus step count, established at
    resume (nonzero only after a changed-global-batch remap, where the step
    counter and the data cursor diverge) — without it, a SECOND resume from
    a checkpoint written after such a remap would reposition from step*G
    and re-train whole spans of data."""
    spe = max(len(loader), 1)
    g = loader.global_batch_examples
    batches = step + batch_delta
    return {"epoch": batches // spe, "offset_batches": batches % spe,
            "consumed_samples": batches * g, "shuffle_seed": seed,
            "global_batch_examples": g, "dataset_len": dataset_len,
            "steps_per_epoch": spe}


def _resume_data_position(mgr: CheckpointManager, resume_step: int,
                          loader: DataLoader, dataset_len: int,
                          seed: int) -> tuple[int, int]:
    """O(1) resume position (start_epoch, start_batch) for the data stream.

    Replaces the seed's O(resume_step) loader replay ("minutes at scale"):
    the checkpoint's data_state pins (seed, dataset_len, consumed samples),
    and index arithmetic alone repositions the samplers. Checkpoints
    without a data_state (pre-elastic format) derive the position from the
    step count — identical to what the old replay computed, still O(1).
    A changed global batch is remapped by consumed-sample count (exact only
    when G is unchanged — re-trains at most one partial batch otherwise,
    and warns); a changed shuffle seed or dataset cannot be remapped and
    falls back to step-count positioning with a warning."""
    spe = max(len(loader), 1)
    g = loader.global_batch_examples
    batches = resume_step
    data_state = None
    try:
        data_state = mgr.load_meta(resume_step).get("data_state")
    except Exception as e:  # meta vanished under us — position by step count
        logger.warning("could not re-read checkpoint-%d meta for data_state "
                       "(%r); positioning the loader by step count",
                       resume_step, e)
    if data_state:
        if (data_state.get("shuffle_seed") != seed
                or data_state.get("dataset_len") != dataset_len):
            logger.warning(
                "checkpoint data_state (seed=%s, dataset_len=%s) does not "
                "match this run (seed=%s, dataset_len=%s); positioning by "
                "step count — the shuffle order differs, sample-exact "
                "continuity is not guaranteed",
                data_state.get("shuffle_seed"), data_state.get("dataset_len"),
                seed, dataset_len)
        else:
            consumed = int(data_state.get("consumed_samples", resume_step * g))
            src_g = data_state.get("global_batch_examples")
            if src_g not in (None, g):
                logger.warning(
                    "global batch changed across resume (%s -> %s examples/"
                    "step); sample-exact continuity only holds for an "
                    "unchanged global batch — remapping by consumed-sample "
                    "count, re-training at most one partial batch "
                    "(docs/RESILIENCE.md)", src_g, g)
            batches = consumed // g
    epoch, offset = divmod(batches, spe)
    logger.info("O(1) data resume: step %d -> epoch %d, batch offset %d "
                "(no loader replay)", resume_step, epoch, offset)
    return epoch, offset


def _note_topology_change(mgr: CheckpointManager, step: int,
                          current: dict) -> None:
    """Log an elastic restore: the checkpoint's recorded source topology vs
    the mesh this incarnation runs. Purely informational — the canonical
    layout + resharded Orbax reads make the restore itself work; what an
    operator needs is the ledger line saying the resize happened."""
    try:
        source = mgr.load_meta(step).get("topology")
    except Exception:
        return
    if not source:
        return  # pre-elastic checkpoint: nothing recorded
    keys = ["pp", "dp", "tp", "sp", "schedule", "virtual_stages"]
    if "layer_counts" in source:
        # the stage PARTITION is restore-relevant like a topology axis (a
        # (4,4,4,1) -> even/2 ladder resize reshards every layer leaf);
        # compared only when the source recorded it, so pre-partition-aware
        # checkpoints don't flag a phantom change on every resume
        keys.append("layer_counts")
    changed = sorted(k for k in keys if source.get(k) != current.get(k))
    if changed:
        logger.warning(
            "elastic restore: checkpoint-%d was written at %s "
            "(schedule=%s, v=%s, layer_counts=%s); restoring onto %s "
            "(schedule=%s, v=%s, layer_counts=%s) — "
            "changed: %s. Keep the global batch unchanged for sample-exact "
            "data continuity (docs/RESILIENCE.md)",
            step, source.get("layout"), source.get("schedule"),
            source.get("virtual_stages"), source.get("layer_counts"),
            current.get("layout"), current.get("schedule"),
            current.get("virtual_stages"), current.get("layer_counts"),
            changed)
    else:
        logger.info("resume topology matches checkpoint-%d (%s)", step,
                    current.get("layout"))


def _restore_with_fallback(mgr: CheckpointManager, restore_fn) -> Any | None:
    """Resume restore with automatic fallback (docs/RESILIENCE.md): when the
    newest checkpoint fails integrity verification, `verify` quarantines it
    to checkpoint-N.corrupt, `latest_step()` then resolves to the previous
    complete one, and the restore simply re-runs — until a checkpoint
    verifies or none remain (fresh start). Only CheckpointCorruptError
    falls back; layout/compat errors (ValueError) stay fatal — they mean a
    misconfigured run, and silently training from an older checkpoint would
    hide that."""
    prev: int | None = None
    while True:
        step = mgr.latest_step()
        if step is None:
            return None
        if step == prev:
            # quarantine could not move the dir (permissions?) — re-raising
            # beats spinning on the same corrupt checkpoint forever
            raise CheckpointCorruptError(
                f"checkpoint-{step} is corrupt and could not be quarantined")
        try:
            return restore_fn(step)
        except CheckpointCorruptError as e:
            logger.error("resume blocked by corrupt checkpoint-%d (%s); "
                         "falling back", step, e)
            prev = step


def _summarize(final_loss, preempted_at, end_step, steps_per_epoch,
               output_dir) -> dict:
    """The run summary contract shared by both optimizer paths: final_step is
    the step the run actually stopped at (a preempted run never reached
    end_step)."""
    return {"final_step": end_step if preempted_at is None else preempted_at,
            "final_loss": final_loss, "preempted_at": preempted_at,
            "steps_per_epoch": steps_per_epoch, "output_dir": output_dir}


def _sync_checkpoint(cfg: dict, path: str) -> None:
    """Off-node durability hook (reference `./s5cmd sync` after each save,
    trainer_base_ds_mp.py:220): run `save_sync_command` with {path}
    substituted, on process 0, after the checkpoint is durably on disk.
    e.g.  save_sync_command: "gsutil -m rsync -r {path} gs://bucket/run/"
    Failures are logged, never fatal — a sync outage must not kill training.
    """
    command = cfg.get("save_sync_command")
    if not command or jax.process_index() != 0:
        return
    import subprocess

    # plain replace (not str.format): the command may contain shell braces
    cmd = command.replace("{path}", path)
    try:
        result = subprocess.run(cmd, shell=True, capture_output=True, text=True,
                                timeout=cfg.get("save_sync_timeout", 1800))
        if result.returncode != 0:
            logger.warning("save_sync_command failed (%d): %s", result.returncode,
                           result.stderr.strip()[-500:])
        else:
            logger.info("checkpoint synced: %s", cmd)
    except Exception as e:  # timeout / spawn failure — never kill training
        logger.warning("save_sync_command error: %r", e)


def _make_evaluator(cfg, mesh, model_cfg, pcfg, stacked_template, attn_fn,
                    get_params):
    """Optional held-out evaluation (cfg `eval_dataset` node + `eval_steps`).

    The reference shipped only dead eval config (`do_eval`, absent evaluator
    classes — SURVEY.md §2.4); this closes that gap with a loss-only pipeline
    pass over an eval loader."""
    eval_cfg = cfg.get("eval_dataset")
    if eval_cfg is None:
        return None
    eval_ds, eval_coll = build_dataset_and_collator(
        {**cfg, "dataset": eval_cfg}, model_cfg)
    mesh_dp = mesh.shape["dp"]
    per_replica = (cfg.get("per_device_eval_batch_size",
                           cfg.get("per_device_train_batch_size", 1))
                   * pcfg.num_microbatches
                   * _packing_factor(cfg))
    eval_loader = DataLoader(eval_ds, eval_coll, per_replica_batch=per_replica,
                             dp_size=mesh_dp, shuffle=False,
                             dp_range=host_dp_shard(mesh))
    if len(eval_loader) == 0:
        raise ValueError("eval dataset too small for one batch")
    eval_fn = jax.jit(pl.make_pipeline_eval_fn(mesh, model_cfg, pcfg,
                                               stacked_template, attn_fn=attn_fn))

    def run_eval():
        total, tokens = 0.0, 0
        for batch in eval_loader:
            loss_sum, count = eval_fn(get_params(), form_global_batch(mesh, batch))
            total += float(loss_sum)
            tokens += int(count)
        return total / max(tokens, 1)  # exact token mean, not mean-of-means

    return run_eval


def _packing_scalars(collator) -> Any:
    """Metrics hook surfacing the packed collator's cumulative drop counters
    (round-3 weak #4: drops warned once per process and never reached the
    metrics stream). Counters are this process's own loader traffic — on a
    pod each host packs its dp shards, so process 0's rate is a same-
    distribution sample, not the global count."""
    if not isinstance(collator, PackedCausalLMCollator):
        return None

    def scalars():
        return {"packing_dropped_total": collator.dropped_total,
                "packing_drop_rate": round(collator.drop_rate(), 4)}

    return scalars


def _host_scalars(collator, loader) -> Any:
    """All host-side per-line counters: the packing drop counters plus the
    loader's record-quarantine count (only when the quarantine is armed —
    an always-zero column on every healthy run would be noise)."""
    packing = _packing_scalars(collator)
    if not loader.quarantine_bad_records:
        return packing

    def scalars():
        out = packing() if packing else {}
        out["data_quarantined_records"] = loader.quarantine_count
        return out

    return scalars


def _train_loop(cfg, model_cfg, mesh, loader, seq_length, resume_step, end_step,
                do_step, do_save, do_eval=None, extra_scalars=None,
                static_scalars=None, monitor=None, data_start=(0, 0),
                health_static=None, step_timeline=None, profiler=None,
                mem_watch=None) -> tuple:
    """The shared step/log/save/profile loop for both optimizer paths.

    `do_step(batch, step, fault=None) -> (loss_scalar, scalars_thunk)`; the
    thunk is only called at logging boundaries so the hot loop never blocks
    on a D2H sync; `fault` forwards the step-site fault verdict (the
    grad_nonfinite chaos op). `do_save(step)` writes a full checkpoint.
    `do_eval() -> float` (optional) runs every `eval_steps`.
    `extra_scalars() -> dict` (optional) contributes host-side counters
    (e.g. packing drop rate) to every metrics line; `static_scalars`
    (optional dict) are run constants (e.g. the schedule's bubble fraction)
    repeated on every line so downstream joins need no second file.
    `monitor` (numerics.NumericsMonitor, optional) feeds the heartbeat's
    numerics fields and the metrics line's counters; its
    `NonfiniteHaltError` is turned into a final checkpoint + re-raise here.
    `data_start` ((epoch, batch), from _resume_data_position) opens the
    repeating loader at the O(1) resume position; `health_static`
    (optional dict, e.g. the run topology) rides on every health.json write.
    `step_timeline` (timeline.StepTimeline, optional — the schedule
    observatory) wraps every step with the collector window, BLOCKS on each
    step's loss (the marks-to-steps barrier), and contributes
    `bubble_fraction_measured` / `step_time_p50/p95` to the metrics line +
    health.json. `profiler` (profiler.TriggeredProfiler, optional) gets
    each iteration's host wall for the step-time z-score trigger, the
    numerics-anomaly span stream, and a close() on every exit path.
    `mem_watch` (memwatch.MemoryWatch, optional — the memory
    observatory) samples the live memory sources after every step and
    feeds the OOM snapshot; the RESOURCE_EXHAUSTED handler below runs
    with or without it (the snapshot degrades to the live poll alone).
    """
    output_dir = cfg["output_dir"]
    # Scalars are replicated across processes: process 0 writes for the pod
    # (reference rank-0 gating, trainer_base_ds_mp.py:360-374).
    writer = (MetricsWriter(output_dir, config_snapshot=cfg,
                            use_wandb=cfg.get("use_wandb", False),
                            use_tensorboard=cfg.get("use_tensorboard", False))
              if jax.process_index() == 0 else NullMetricsWriter())
    # This host's batches cover only its own dp shards; scale the meter's
    # counts to the global batch (n_chips is the global chip count).
    _, local_dp = host_dp_shard(mesh)
    meter = Throughput(model_cfg, seq_length, n_chips=mesh.devices.size,
                       global_scale=mesh.shape["dp"] / local_dp)
    logging_steps = cfg.get("logging_steps", 10)
    save_steps = cfg.get("save_steps", 0)

    # ---- run-health telemetry (docs/OBSERVABILITY.md) ---------------------
    # Everything since trace.configure() — model build, restore, data probe —
    # is the init bucket; record it retroactively as a span so the offline
    # goodput report's bucket sum matches wall-clock.
    rec = trace.recorder()
    if profiler is not None:
        # numerics-anomaly spans become bounded captures (utils/profiler.py)
        rec.add_listener(profiler.on_span)
    rec.emit("init", rec.configured_at, time.time() - rec.configured_at)
    # Resume carries the previous incarnation's cumulative buckets forward:
    # goodput stays a whole-run number, and the wall time the preemption
    # threw away surfaces as badput instead of vanishing with the restart.
    prior = trace.load_health(output_dir) if resume_step else None
    init_secs = time.time() - rec.configured_at
    clock = trace.RunClock(prior=(prior or {}).get("clock"),
                           already_elapsed=init_secs)
    clock.add("init", init_secs)
    rec.add_listener(clock.on_span)
    # LIVE health.json contributions: the numerics monitor's fields plus the
    # timeline's rolling bubble_fraction_measured / step_time percentiles —
    # a ChainMap so both owners keep mutating their own dict between writes
    import collections as _collections

    live_fields = [m for m in (
        monitor.health_fields if monitor is not None else None,
        step_timeline.health_fields if step_timeline is not None else None)
        if m is not None]
    heartbeat = (trace.Heartbeat(output_dir, clock,
                                 interval=cfg.get("health_interval", 10.0),
                                 extra=(_collections.ChainMap(*live_fields)
                                        if live_fields else None),
                                 static=health_static)
                 if jax.process_index() == 0 else None)
    peak_bytes, peak_src = trace.device_peak_bytes()
    logger.info("device memory telemetry: %s (%s)",
                "unavailable" if peak_bytes is None else f"{peak_bytes} B peak",
                peak_src)

    # Optional profiler capture window: profile_steps: [start, stop] writes a
    # tensorboard/Perfetto trace under <output_dir>/profile (SURVEY.md §5.1 —
    # the reference had only DeepSpeed's steps_per_print throughput line).
    # Clamped into [resume_step, end_step] so resume/short runs stay safe.
    profile_window = cfg.get("profile_steps")
    if profile_window:
        lo = max(int(profile_window[0]), resume_step)
        hi = min(int(profile_window[1]), end_step)
        if lo >= hi:
            logger.info("profile_steps %s empty after clamping to [%d, %d); "
                        "skipping trace", list(profile_window), resume_step, end_step)
            profile_window = None
        else:
            profile_window = (lo, hi)
    trace_active = False

    # O(1) data resume (docs/RESILIENCE.md "Elastic resume"): the loader
    # opens directly at (epoch, batch) by index arithmetic — the reference's
    # batch-by-batch fast-forward replay (reference :345-351, "minutes at
    # scale") and its PR 1 descendant are gone.
    start_epoch, start_batch = data_start
    it: Iterator = iter(RepeatingLoader(loader, start_epoch=start_epoch,
                                        start_batch=start_batch))
    it = PrefetchIterator(it, depth=cfg.get("prefetch_depth", 2))

    # Preemption-aware save (SURVEY.md §5.3): on a preemption notice —
    # Python-handler flag (single-process / pre-init window) or the
    # coordination service's sync point (pod) — finish the current step,
    # checkpoint, exit cleanly so the next run resumes instead of losing the
    # interval. Handlers are installed by run_training before distributed
    # init; see _install_preemption_handlers.
    losses: list = []  # jax scalars; fetched only at logging boundaries
    final_loss = float("nan")
    preempted_at = None  # the step THIS process observed the stop at
    last_saved = -1
    completed = resume_step  # steps whose update the live state reflects
    # Pods agree on preemption via a host collective; running it every step
    # would sync the hot loop, so check on a fixed cadence — the SAME steps on
    # every host (the decision must never depend on a host-local flag, or the
    # allgather call counts diverge and the pod hangs).
    check_every = max(int(cfg.get("preempt_check_every", 10)), 1)
    # actions.resize_on_request (docs/RESILIENCE.md "Actuation"): poll for
    # the autoscaler's resize.request on the same uniform cadence. The
    # config is process-uniform, so the extra _should_stop allgather below
    # is called identically everywhere — collective counts stay aligned.
    from llama_pipeline_parallel_tpu.utils.actions import TrainActions

    resize_watch = TrainActions.from_cfg(cfg.get("actions")).resize_on_request
    _LAST_EVAL.clear()  # a fresh loop must not inherit a prior run's eval
    window_t0 = time.perf_counter()
    window_overhead = 0.0  # compile/eval/ckpt seconds to exclude from step_time

    try:
        for step in range(resume_step, end_step):
            # per-iteration host wall, taken BEFORE the fault hook so a
            # `slow` chaos rule at the step site lands in the measured wall
            # the profiler's z-score trigger watches (docs/OBSERVABILITY.md
            # "Triggered capture")
            iter_t0 = time.perf_counter()
            # chaos hook: a `die`/`stall` rule at a chosen step simulates
            # preemption or a hung pod at an exact, reproducible point; a
            # `grad_nonfinite` verdict rides into do_step to poison the
            # jitted step's gradients (numerics observatory chaos input)
            fault_verdict = faults.fire("step", step=step)
            if fault_verdict == "oom":
                # synthetic allocation failure (chaos op `oom`): raised
                # HERE, inside the loop's try, so it exercises the REAL
                # RESOURCE_EXHAUSTED forensics path below — snapshot,
                # supervisor `oom` outcome, fleet `oom_recent` alert
                raise RuntimeError(
                    f"RESOURCE_EXHAUSTED: Out of memory while running "
                    f"step {step} (injected oom fault)")
            # The sync point must be polled EVERY step with the loop's step id
            # (the protocol computes max-step+1 as the one safe stop step for
            # the whole pod); it returns True on every process at that same
            # step. The allgather vote covers Python-handler signals on its
            # own cadence.
            preempt_notice = _preemption_notice(step)
            check_now = jax.process_count() == 1 or step % check_every == 0
            # Both stop inputs are evaluated into locals BEFORE combining:
            # _should_stop's allgather is a collective, so its call count must
            # be identical on every process every step. Short-circuiting it
            # behind preempt_notice would only be safe because the sync point
            # fires process-uniformly — keep the uniformity structural.
            stop_vote = check_now and _should_stop(bool(_STOP_SIGNALS))
            # the resize vote rides the same cadence and allgather shape:
            # any process seeing the request stops ALL of them at this step
            resize_vote = (resize_watch and check_now
                           and _should_stop(_resize_requested(output_dir)))
            if preempt_notice or stop_vote or resize_vote:
                logger.warning("%s; checkpointing at step %d and "
                               "exiting for clean resume",
                               "resize request" if resize_vote
                               else "preemption signal", step)
                preempted_at = step
                do_save(step, final=True)
                last_saved = end_step  # suppress the save_final duplicate
                if resize_vote and jax.process_index() == 0:
                    # ack AFTER the save commits: the request must outlive
                    # a crash-mid-save so the next incarnation re-honors it
                    _ack_resize_request(output_dir)
                break
            if profile_window and not trace_active and step >= profile_window[0] \
                    and step < profile_window[1]:
                jax.profiler.start_trace(os.path.join(output_dir, "profile"))
                trace_active = True
            with trace.span("data_wait", step=step):
                batch = next(it)
            if step_timeline is not None:
                step_timeline.pre_step(step + 1)
            try:
                if step == resume_step:
                    # First step: trace+XLA-compile happen synchronously
                    # inside the dispatch, and the value barrier catches the
                    # rest — so the whole first-step wall time lands in the
                    # compile bucket instead of smearing into the first
                    # window's train time.
                    with trace.span("compile_block", step=step) as sp:
                        loss, scalars_thunk = do_step(batch, step + 1,
                                                      fault=fault_verdict)
                        jax.block_until_ready(loss)
                    window_overhead += sp["dur"]  # compile not in step_time
                else:
                    with trace.span("step_dispatch", step=step):
                        loss, scalars_thunk = do_step(batch, step + 1,
                                                      fault=fault_verdict)
            except numerics.NonfiniteHaltError:
                # the monitor raises AFTER do_step committed this step's
                # state — record that so the halt save labels it correctly
                completed = step + 1
                raise
            completed = step + 1
            if step_timeline is not None:
                # block-on-boundary: the marks-to-steps barrier (and the
                # measured step wall) — the timeline mode's documented cost
                step_timeline.post_step(step + 1, loss)
            if mem_watch is not None:
                # host-side poll only (memory_stats + RSS) — never touches
                # the dispatched computation; `memory.every` rate-limits it
                mem_watch.sample(step + 1)
            if profiler is not None:
                # compile step excluded from the z-score baseline (a 100x
                # wall would deflate every later z); it still advances an
                # open capture window
                profiler.observe_step(
                    step + 1, None if step == resume_step
                    else time.perf_counter() - iter_t0)
            if heartbeat is not None:
                heartbeat.beat(step + 1)
            if trace_active and (step + 1 >= profile_window[1] or step + 1 == end_step):
                jax.block_until_ready(loss)
                jax.profiler.stop_trace()
                trace_active = False
                logger.info("profiler trace written to %s/profile", output_dir)
            losses.append(loss)
            mask = batch.get("attention_mask")
            meter.update(batch["input_ids"].size,
                         real_tokens=None if mask is None
                         else int((mask != 0).sum()))
            if (step + 1) % logging_steps == 0 or step + 1 == end_step:
                n_window = len(losses)
                # the value fetch is the loop's sync point: its wall time is
                # the device executing the window's steps (minus what the
                # dispatch/data spans already took on the host side)
                with trace.span("device_step", step=step + 1, steps=n_window):
                    final_loss = float(losses[-1])
                # pure stepping time: compile/eval/ckpt wall time inside the
                # window is subtracted, so step_time tracks the train rate
                # (those phases are visible in the goodput buckets instead)
                step_dur = max(time.perf_counter() - window_t0 - window_overhead,
                               0.0) / max(n_window, 1)
                window_t0 = time.perf_counter()
                window_overhead = 0.0
                peak_bytes, _ = trace.device_peak_bytes()
                writer.log(step + 1, {"loss": float(np.mean([float(l) for l in losses])),
                                      **scalars_thunk(), **meter.read_and_reset(),
                                      **(extra_scalars() if extra_scalars else {}),
                                      **(static_scalars or {}),
                                      **(monitor.scalars() if monitor is not None
                                         else {}),
                                      **(step_timeline.scalars()
                                         if step_timeline is not None else {}),
                                      "goodput": round(clock.goodput(), 4),
                                      "step_time": round(step_dur, 4),
                                      "device_peak_bytes": peak_bytes})
                if heartbeat is not None:
                    heartbeat.beat(step + 1, step_dur)
                losses.clear()
            eval_steps = cfg.get("eval_steps", 0)
            if do_eval is not None and eval_steps and (step + 1) % eval_steps == 0:
                with trace.span("eval", step=step + 1) as sp:
                    eval_loss = do_eval()
                writer.log(step + 1, {"eval_loss": eval_loss})
                # later checkpoints carry this as their deployment gate
                _LAST_EVAL.update(step=step + 1, loss=float(eval_loss))
                window_overhead += sp["dur"]
            if save_steps and (step + 1) % save_steps == 0:
                t_save = time.perf_counter()
                do_save(step + 1)
                last_saved = step + 1
                window_overhead += time.perf_counter() - t_save
        if monitor is not None:
            # drain the lag-1 queue: the LAST step's nonfinite verdict must
            # fire (halt included) before the final save decides what state
            # it is committing
            monitor.flush()
    except numerics.NonfiniteHaltError as e:
        # halt_on_nonfinite: the nonfinite update was already where-skipped
        # in-graph, so the live state is finite — commit it through the PR 2
        # checkpoint path, then exit nonzero (the supervisor's crash-loop
        # budget sees a short, clean abort instead of hours of NaN steps).
        # Save under `completed`, NOT e.step: the monitor's lag-1 fetch means
        # the halt surfaces one step after the nonfinite one, and by then the
        # state already reflects that later (clean, or also-skipped) step —
        # labeling it e.step would make a resume re-apply a batch.
        logger.error("halting on nonfinite gradients at step %d; writing a "
                     "final checkpoint at step %d before exiting nonzero",
                     e.step, completed)
        do_save(completed, final=True)
        raise
    except Exception as e:
        if not memwatch_mod.is_resource_exhausted(e):
            raise
        # OOM forensics (docs/OBSERVABILITY.md "Memory"): the process is
        # about to die — write the bounded snapshot FIRST (the supervisor
        # labels the incarnation `oom` off its mtime, the fleet observatory
        # alerts on it), then re-raise the original error. No final save:
        # after a real allocation failure the device state is not
        # trustworthy, and a hung save would turn a crisp abort into a hang.
        logger.error("allocation failure at step %d; writing OOM snapshot "
                     "to %s before exiting", completed,
                     memwatch_mod.oom_dir(output_dir))
        memwatch_mod.dump_oom_snapshot(output_dir, completed, e,
                                       memwatch=mem_watch)
        if profiler is not None:
            profiler.trigger("oom", completed)
        raise
    finally:
        if trace_active:  # preemption break / exception inside the window
            jax.profiler.stop_trace()
            logger.info("profiler trace (early exit) written to %s/profile", output_dir)
        if profiler is not None:
            rec.remove_listener(profiler.on_span)
            profiler.close()  # a capture window open at exit is finalized
        if step_timeline is not None:
            step_timeline.close()
        if mem_watch is not None:
            mem_watch.close()
        if monitor is not None:
            monitor.close()
        loader.close_ledger()  # repeated in-process runs must not leak fds
        writer.close()
        if heartbeat is not None:
            heartbeat.stop()  # kills the daemon on every exit path; write()
            # below still works for the final save's post-stop refresh
        # The loop is over on every path out of here: nothing re-checks
        # _STOP_SIGNALS anymore, so holding the graceful handlers would
        # silently swallow a Ctrl+C during the final save or during
        # run_training's async-commit join on the exception path. Hand the
        # signals back (pre-refactor behavior: an interrupt there raises
        # KeyboardInterrupt immediately).
        _release_preemption_handlers()
    if cfg.get("save_final", True) and last_saved != end_step:
        do_save(end_step, final=True)
        if heartbeat is not None:  # clock listener saw the ckpt_save span;
            heartbeat.write()      # fold the final save into health.json
    return final_loss, preempted_at


def _preemption_notice(step: int) -> bool:
    """Poll the JAX coordination service's preemption sync point.

    Once `jax.distributed.initialize()` registers the preemption sync
    manager, its C++ notifier owns SIGTERM (preemption_notifier.cc) — the
    Python handlers never fire, no matter when they were installed. The
    notifier feeds the service, which propagates the notice to every process
    and picks one safe stop step (max current step + 1); this returns True
    on all processes at exactly that step. Without the sync manager
    (single-process, or service disabled by config) it is a no-op and the
    Python-handler path applies."""
    if not _cpp_notifier_owns_sigterm():
        return False
    from jax.experimental import multihost_utils

    return bool(multihost_utils.reached_preemption_sync_point(step))


# the most recent eval_loss, keyed into every later checkpoint's meta.json
# (via do_save's extra_meta) — the continuous-deployment gate's input
# (utils/actions.Deployer): a deploy/rollback decision needs the QUALITY of
# a checkpoint, not just its existence. A module box, like _STOP_SIGNALS:
# the eval happens in _train_loop but the save closures live in its callers.
_LAST_EVAL: dict = {}


def _eval_meta() -> dict:
    """extra_meta contribution: the last eval_loss (and the step it was
    measured at) — empty before the first eval so a never-evaluated run
    writes no fabricated gate value."""
    if "loss" in _LAST_EVAL:
        return {"eval_loss": _LAST_EVAL["loss"],
                "eval_step": _LAST_EVAL["step"]}
    return {}


def _resize_requested(output_dir: str) -> bool:
    """Poll for an actuator's `resize.request` drop (utils/actions): the
    fleet autoscaler asking this trainer to step down/up a ladder rung at
    a step boundary instead of eating a SIGTERM mid-step."""
    from llama_pipeline_parallel_tpu.utils.actions import RESIZE_REQUEST_NAME

    return os.path.exists(os.path.join(output_dir, RESIZE_REQUEST_NAME))


def _ack_resize_request(output_dir: str) -> None:
    """Rename `resize.request` -> `resize.request.ack` (atomic on POSIX):
    the actuator/test sees the trainer honored the request exactly once;
    a crash before the rename leaves the request for the relaunched
    incarnation — at-least-once, and the rename dedups."""
    from llama_pipeline_parallel_tpu.utils.actions import (
        RESIZE_ACK_NAME,
        RESIZE_REQUEST_NAME,
    )

    try:
        os.replace(os.path.join(output_dir, RESIZE_REQUEST_NAME),
                   os.path.join(output_dir, RESIZE_ACK_NAME))
    except OSError:
        pass  # already acked by a peer process, or never landed locally


def _should_stop(local_flag: bool) -> bool:
    """Agree on preemption across hosts: a one-host signal must stop ALL
    processes at the same step, or the save barrier deadlocks against peers
    still running the jitted step's collectives."""
    if jax.process_count() == 1:
        return local_flag
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(np.asarray(local_flag, np.int32))
    return bool(np.any(flags))


def _run_offload(cfg, mesh, model_cfg, manifest, pcfg, ocfg, dataset, collator,
                 loader, end_step, stacked_template, mgr, ncfg=None,
                 monitor=None) -> dict:
    """Host-offloaded-optimizer training setup (reference ZeRO-offload path,
    conf yaml:160-162): fp32 masters + Adam moments in host DRAM via
    optim/offload.py; the device holds only the bf16 working copy and runs
    loss+grad. Grads stream D2H (async, overlapped with the host kernel),
    fresh bf16 params H2D (host-cast, half the bytes), every step. Masters
    are sharded per process: each host keeps/updates only the shards its
    devices hold (the ZeRO-offload distribution of the reference's 800 GB
    65B state, README.md:70-71).

    `optimizer_offload_zero2: true` (dp>1): masters, moments, AND the
    gradient outputs are additionally dp-sharded on each leaf's rightmost
    free dim (reference ZeRO-2 `reduce_scatter: True`, conf yaml:152-159,
    lifted to the host tier) — host DRAM, grad D2H bytes, and host AdamW
    work all drop to 1/dp per host; the device re-gathers the bf16 working
    copy over the dp axis once per step (ICI all-gather)."""
    from llama_pipeline_parallel_tpu.optim.offload import HostOffloadAdamW

    output_dir = cfg["output_dir"]
    if ncfg is None:
        ncfg = numerics.NumericsConfig.from_cfg(cfg.get("numerics"))
    zero2 = bool(cfg.get("optimizer_offload_zero2"))
    if zero2 and mesh.shape["dp"] == 1:
        logger.info("optimizer_offload_zero2 has no effect at dp=1; "
                    "running the plain offload layout")
        zero2 = False
    if zero2:
        z2_shardings = ts.specs_to_shardings(
            mesh, ts.zero2_param_specs(stacked_template, mesh))
        # reshard the freshly-initialized masters-to-be dp-sharded BEFORE
        # the host copies them out; each host then stores only 1/dp.
        # (No donation: a replicated->sharded reshard can never alias
        # layouts, and the dead donate only emits unusable-buffer warnings.)
        stacked_template = jax.jit(
            lambda p: p, out_shardings=z2_shardings)(stacked_template)
    # device-side grad norm (default): frees the fused step to stream
    # leaf-by-leaf instead of waiting for the full-tree grad D2H before the
    # first AdamW; offload_device_norm: false restores the host fp64 norm
    host = HostOffloadAdamW(ocfg,
                            skip_nonfinite=ncfg.enabled,
                            device_norm=cfg.get("offload_device_norm", True))
    host.init(stacked_template)
    # fp32 masters now live on the host; drop the device fp32 init copy and
    # keep only SHARDED abstract structs as the template (HBM holds just the
    # bf16 working copy; restores place arrays pre-sharded from these)
    stacked_template = host.abstract_tree()

    resume_step = 0

    def _restore_offload(resume: int) -> int:
        meta = mgr.load_meta(resume)
        if not meta.get("has_optimizer_state"):
            raise ValueError(
                f"checkpoint-{resume} has no optimizer state (module-only / "
                f"converter output); point model_name_or_path at it instead")
        layout = meta.get("opt_layout")
        if layout != "offload_parts":
            writer = ("the fused (optax) optimizer" if layout is None
                      else f"an unknown optimizer layout {layout!r}")
            raise ValueError(
                f"checkpoint-{resume}'s optimizer state was written by "
                f"{writer}, not the current offload layout. To continue "
                f"those weights under the offloaded optimizer, point "
                f"model_name_or_path at this checkpoint and use a fresh "
                f"output_dir (module-only warm start; optimizer moments "
                f"restart).")
        # Multi-host restore works end to end: the templates carry mesh
        # shardings (host.abstract_tree + the sharding-preserving canonical
        # reshape), Orbax restores each host's shards locally, and _scatter
        # reads only addressable shards — executed across real processes by
        # tests/test_multiprocess.py::test_offload_trainer_two_process_resume.
        # load_params runs the integrity pass over the WHOLE dir, so the
        # moments restore below skips its own (verify=False — hash once).
        host.load_masters(mgr.load_params(resume, stacked_template, manifest))
        m, v, step_count = mgr.load_offload_moments(resume, stacked_template,
                                                    manifest, verify=False)
        host.load_state_dict({"m": m, "v": v, "step_count": step_count})
        return resume

    topology = _topology_meta(mesh, pcfg, manifest)
    restored = (_restore_with_fallback(mgr, _restore_offload)
                if cfg.get("resume", True) else None)
    if restored is not None:
        resume_step = restored
        logger.info("resumed offloaded state from checkpoint-%d", resume_step)
        _note_topology_change(mgr, resume_step, topology)
    elif cfg.get("model_name_or_path"):
        warm = CheckpointManager(cfg["model_name_or_path"])
        warm_step = warm.latest_step()
        if warm_step is None:
            raise FileNotFoundError(f"no checkpoint under {cfg['model_name_or_path']}")
        host.load_masters(warm.load_params(warm_step, stacked_template, manifest))
        logger.info("warm-started offloaded masters from %s", cfg["model_name_or_path"])

    seq_length = int(collator([dataset[0]])["input_ids"].shape[1])
    if seq_length % mesh.shape["sp"]:
        raise ValueError(f"sequence length {seq_length} must divide into "
                         f"sp={mesh.shape['sp']} equal slabs")
    attn_fn = select_attention(cfg.get("attention", "auto"), seq_length, mesh,
                               sequence_parallel=cfg.get("sequence_parallel", "ring"),
                               model_cfg=model_cfg,
                               packed=_packing_factor(cfg) > 1,
                               micro_batch=cfg.get("per_device_train_batch_size", 1))
    step_tl, prof, mem_watch = _make_observatory(
        cfg, pcfg, output_dir,
        stash_bytes=pl.host_stash_bytes(pcfg, *pl.stash_dims(
            cfg.get("per_device_train_batch_size", 1), seq_length,
            mesh.shape["sp"], model_cfg.hidden_size, model_cfg.dtype)))
    loss_and_grad = pl.make_pipeline_loss_and_grad(
        mesh, model_cfg, pcfg, stacked_template, attn_fn=attn_fn,
        collect_stats=ncfg.enabled,
        timeline_segments=step_tl is not None and step_tl.segmented)
    from jax.sharding import NamedSharding, PartitionSpec

    def _replicate_stats(stats):
        # stat outputs must be replicated (the shard_map leaves act stats
        # pp-sharded): the monitor's host read requires every pod process
        # to hold the full few-hundred-float value
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, PartitionSpec())), stats)

    # The grad_nonfinite chaos op must poison grads BETWEEN loss+grad and
    # the stats, which forces a separate stats dispatch; steady-state runs
    # (no such rule) fold numerics.step_stats into the ONE jitted loss+grad
    # program instead — no second traversal of the gradient tree per step.
    poison_on = faults.has_rule("step", "grad_nonfinite")

    def _grad_with_stats(p, batch):
        loss, grads, act_stats = loss_and_grad(p, batch)
        stats = numerics.step_stats(p, grads,
                                    virtual_stages=pcfg.virtual_stages)
        stats.update(act_stats)
        return loss, grads, _replicate_stats(stats)

    def _grad_chaos(p, batch):
        # chaos mode computes grad/param stats in a separate post-poison
        # dispatch, but the act stats still leave here — replicated, or a
        # pod process couldn't read its non-addressable pp shards
        loss, grads, act_stats = loss_and_grad(p, batch)
        return loss, grads, _replicate_stats(act_stats)

    grad_out = loss_and_grad if not ncfg.enabled else (
        _grad_chaos if poison_on else _grad_with_stats)
    if zero2:
        # grads leave the device dp-SHARDED: GSPMD turns the shard_map's dp
        # psum + the output constraint into a reduce-scatter, and each host
        # then D2H-pulls only its 1/dp of every gradient tree
        out_shardings = ((None, z2_shardings, None) if ncfg.enabled
                         else (None, z2_shardings))
        grad_fn = jax.jit(grad_out, out_shardings=out_shardings)
        # the pipeline consumes dp-REPLICATED bf16 params: re-gather the
        # dp-sharded upload over ICI once per step
        replicated = ts.specs_to_shardings(
            mesh, pl.stage_param_specs(stacked_template,
                                       tp=mesh.shape["tp"] > 1))
        to_replicated = jax.jit(lambda p: p, out_shardings=replicated)
    else:
        grad_fn = jax.jit(grad_out)
        to_replicated = lambda p: p

    device_params_box = [to_replicated(host.device_params(model_cfg.dtype))]
    # chaos-only second dispatch: the stats must see the POISONED grads
    stats_fn = (jax.jit(
        lambda p, g: _replicate_stats(numerics.step_stats(
            p, g, virtual_stages=pcfg.virtual_stages)))
        if ncfg.enabled and poison_on else None)
    poison_fn = jax.jit(numerics.poison_grads)

    def do_step(batch, step, fault=None):
        gbatch = form_global_batch(mesh, batch)
        if mem_watch is not None and "loss_and_grad" not in mem_watch.compiled:
            # the offload path's device program is loss+grad (the
            # optimizer lives on the host): same one-shot AOT capture as
            # the fused path's train_step
            try:
                mem_watch.note_compiled(
                    "loss_and_grad",
                    grad_fn.lower(device_params_box[0], gbatch).compile())
            except Exception as e:
                logger.debug("compiled memory capture failed: %r", e)
        stats = None
        if not ncfg.enabled:
            loss, grads = grad_fn(device_params_box[0], gbatch)
        elif not poison_on:
            loss, grads, stats = grad_fn(device_params_box[0], gbatch)
        else:
            loss, grads, act_stats = grad_fn(device_params_box[0], gbatch)
            stage = numerics.fault_stage(fault)
            if stage >= 0:
                grads = poison_fn(grads, stage)
            stats = stats_fn(device_params_box[0], grads)
            stats.update(act_stats)
        # fused step: per-leaf AdamW overlaps the previous leaf's bf16 cast
        # + H2D upload instead of a serial update-all-then-upload-all
        # (a nonfinite global norm skips the masters update, see
        # HostOffloadAdamW.skip_nonfinite)
        t_opt = time.perf_counter()
        device_params_box[0] = to_replicated(
            host.update_and_refresh(grads, model_cfg.dtype))
        if step_tl is not None:
            # the host optimizer is outside the compiled pipeline, so its
            # phase is measured here instead of by a boundary mark
            step_tl.add_host_segment("optimizer_host",
                                     time.perf_counter() - t_opt)
        if monitor is not None:
            monitor.observe(step, loss, host.last_grad_norm, stats)
        return loss, lambda: {"lr": host.last_lr,
                              "grad_norm": host.last_grad_norm,
                              **{k: round(v, 2)
                                 for k, v in host.last_timings.items()}}

    data_start = (_resume_data_position(mgr, resume_step, loader,
                                        len(dataset), cfg.get("seed", 42))
                  if resume_step else (0, 0))
    data_delta = (data_start[0] * max(len(loader), 1)
                  + data_start[1]) - resume_step

    def do_save(step, final=False):
        # the offload save streams from host masters that the next optimizer
        # step mutates IN PLACE — it must block regardless of async_save
        barrier("pre-save")
        path = mgr.save_offload(step, host, manifest, model_cfg,
                                keep_last=cfg.get("save_total_limit"),
                                extra_meta={"topology": topology,
                                            "data_state": _data_state(
                                                step, loader, len(dataset),
                                                cfg.get("seed", 42),
                                                data_delta),
                                            **_eval_meta()})
        _sync_checkpoint(cfg, path)

    do_eval = _make_evaluator(cfg, mesh, model_cfg, pcfg, stacked_template,
                              attn_fn, lambda: device_params_box[0])
    off_static = _offload_static(pcfg, *pl.stash_dims(
        cfg.get("per_device_train_batch_size", 1), seq_length,
        mesh.shape["sp"], model_cfg.hidden_size, model_cfg.dtype))
    final_loss, preempted_at = _train_loop(
        cfg, model_cfg, mesh, loader, seq_length,
        resume_step, end_step, do_step, do_save, do_eval,
        extra_scalars=_host_scalars(collator, loader),
        static_scalars={**_schedule_static_scalars(pcfg), **off_static},
        monitor=monitor, data_start=data_start,
        health_static={**_schedule_health_static(pcfg, topology),
                       **off_static},
        step_timeline=step_tl, profiler=prof, mem_watch=mem_watch)
    _write_perf_rows(cfg, pcfg, output_dir, step_tl, mem_watch)
    return _summarize(final_loss, preempted_at, end_step, len(loader),
                      output_dir)
